#!/bin/bash
# Round-long TPU capture watcher (VERDICT r4 item 1).
#
# Probes the tunneled chip on a timer; at the first healthy probe it runs
# the full bench session and exits 0 so the caller can commit the
# artifacts immediately.  A probe that initializes but fails the matmul
# gate does NOT trigger a capture (tools/tpu_probe.py rc gate).
#
# The capture label comes from BF_BENCH_ROUND (default: rYYYYMMDD UTC of
# the capture), so artifacts are stamped with when they were measured
# instead of a hardcoded round number that silently goes stale.
#
# Artifacts on success (ROUND = $BF_BENCH_ROUND):
#   BENCH_${ROUND}.json       - the driver-format one-line JSON from bench.py
#   BENCH_SUITE_${ROUND}.json - per-config detail written by run_suite_into
#   BENCH_OBS_${ROUND}.json   - observability overhead gate (config 8 with
#                               spans on vs off; tools/obs_overhead.py)
#   BENCH_E2E_${ROUND}.json   - end-to-end observability gate (config 12:
#                               full-stack overhead on the config-8 chain +
#                               two-pipeline loopback SLO/trace-merge run;
#                               tools/e2e_gate.py)
#   BENCH_BATCH_${ROUND}.json - macro-gulp batch gate (config 9 on CPU:
#                               K=16 >= K=1 min-of-N, alternating arm
#                               order; tools/batch_gate.py)
#   BENCH_SEGMENT_${ROUND}.json - compiled-segment gate (config 16 on
#                               CPU: BF_SEGMENTS=auto fuses the unfused
#                               device chain into one program, byte-
#                               identical, zero member dispatches, both
#                               interior rings elided, no regression vs
#                               the hand-fused K=16 arm;
#                               tools/segment_gate.py)
#   BENCH_BEAM_${ROUND}.json  - quantized beamformer gate (config 13 on
#                               CPU: quantized winner beats the f32
#                               baseline arm, within accuracy class,
#                               deterministic; tools/beam_gate.py)
#   MULTICHIP_${ROUND}.json   - mesh pipeline gate (config 11 on an
#                               8-device host mesh: sharded arm matches
#                               single-device, zero-reshard plans;
#                               tools/mesh_gate.py)
#   VERIFY_GATE_${ROUND}.json - static verify gate (every pipeline-shaped
#                               bench topology + every example linted by
#                               the pipeline verifier; tools/verify_gate.py,
#                               strict: any BF-E fails the round up front)
#   CHAOS_SOAK_${ROUND}.json  - chaos/soak gate (config 15 on CPU: a
#                               bridged two-process pipeline under a
#                               scripted overload+kill+fault schedule —
#                               no deadlock, no silent loss, health
#                               SHEDDING->OK, p99 under BF_SLO_MS;
#                               tools/chaos_gate.py)
#   SERVICE_${ROUND}.json     - multi-tenant service gate (config 18 on
#                               CPU: 3 concurrent tenant jobs — replay
#                               + file ingest + synthetic capture —
#                               with paced quotas enforced within 10%,
#                               a BF_FAULTS-killed tenant contained
#                               (survivors DONE/OK, zero cross-tenant
#                               shed/poison), and a warm job start
#                               >= 2x faster than cold with 0
#                               recompiles; tools/service_gate.py)
#   FABRIC_CHAOS_${ROUND}.json - fabric chaos gate (config 17 on CPU:
#                               a 4-process loopback fabric survives a
#                               SIGKILL'd capture host — rejoin replays
#                               only unacked frames, dead origin gapped
#                               not stalled, produced == delivered +
#                               shed byte-exact; tools/fabric_gate.py)
#   SCHED_CHAOS_${ROUND}.json - elastic control-plane gate (config 20
#                               on CPU: a SIGKILL'd host's tenants
#                               re-place automatically onto survivors
#                               as warm zero-recompile starts resuming
#                               from the durable ledger frontier;
#                               displacement sheds by policy and the
#                               cross-tenant arbiter restores the SLO
#                               violator; tools/sched_gate.py)
#   bench_watch.log           - probe/attempt history (gitignored)
cd "$(dirname "$0")/.." || exit 1
ROUND="${BF_BENCH_ROUND:-r$(date -u +%Y%m%d)}"
export BF_BENCH_ROUND="$ROUND"
OUT="BENCH_${ROUND}.json"
LOG=bench_watch.log
echo "$(date -u +%FT%TZ) watcher start pid=$$ round=$ROUND" >> "$LOG"

# Tier-1 gate: run the CPU suite under a hard timeout with the stall
# watchdog armed.  A HUNG run (a regression back to the silent
# pipeline-hang failure mode — timeout rc 124/137) fails the watcher
# fast with a non-zero exit instead of wedging it for the whole round;
# ordinary test failures are logged but do not block the bench capture
# (the driver's own tier-1 gate judges those).  BF_SKIP_T1_GATE=1 opts
# out.
if [ "${BF_SKIP_T1_GATE:-0}" != "1" ]; then
  T1_TIMEOUT="${BF_T1_TIMEOUT:-870}"
  echo "$(date -u +%FT%TZ) tier-1 gate (timeout ${T1_TIMEOUT}s)" >> "$LOG"
  timeout -k 10 "$T1_TIMEOUT" env JAX_PLATFORMS=cpu \
    BF_WATCHDOG_SECS="${BF_WATCHDOG_SECS:-120}" BF_WATCHDOG_ESCALATE=1 \
    python -m pytest tests/ -q -m 'not slow' \
      --continue-on-collection-errors -p no:cacheprovider \
      > "t1_gate_${ROUND}.log" 2>&1
  t1rc=$?
  echo "$(date -u +%FT%TZ) tier-1 gate rc=$t1rc" >> "$LOG"
  if [ "$t1rc" -eq 124 ] || [ "$t1rc" -eq 137 ]; then
    echo "$(date -u +%FT%TZ) tier-1 HUNG past the watchdog timeout - failing fast" >> "$LOG"
    exit "$t1rc"
  fi
fi
# Static verify gate: lint every pipeline-shaped bench topology and
# every example with the pipeline verifier (tools/verify_gate.py ->
# tools/bf_lint.py).  Purely static — runs before the TPU probe loop
# so a misconfigured topology fails the round in seconds, not after a
# full capture.  BF_SKIP_VERIFY_GATE=1 opts out.
if [ "${BF_SKIP_VERIFY_GATE:-0}" != "1" ]; then
  echo "$(date -u +%FT%TZ) static verify gate (bench topologies + examples)" >> "$LOG"
  python tools/verify_gate.py --strict --out "VERIFY_GATE_${ROUND}.json" >> "$LOG" 2>&1
  vrc=$?
  echo "$(date -u +%FT%TZ) verify gate rc=$vrc" >> "$LOG"
  if [ "$vrc" -ne 0 ]; then
    echo "$(date -u +%FT%TZ) static verify gate FAILED" >> "$LOG"
    exit "$vrc"
  fi
fi
for i in $(seq 1 400); do
  out=$(BF_PROBE_DEADLINE=120 timeout 180 python tools/tpu_probe.py 2>/dev/null)
  rc=$?
  echo "$(date -u +%FT%TZ) probe[$i] rc=$rc $out" >> "$LOG"
  if [ "$rc" -eq 0 ]; then
    echo "$(date -u +%FT%TZ) healthy - starting full bench" >> "$LOG"
    timeout 5400 python bench.py > "$OUT.tmp" 2> "bench_${ROUND}.stderr"
    brc=$?
    echo "$(date -u +%FT%TZ) bench rc=$brc" >> "$LOG"
    if [ "$brc" -eq 0 ] && grep -q '"vs_baseline"' "$OUT.tmp" \
        && ! grep -q '"error": "jax backend' "$OUT.tmp"; then
      mv "$OUT.tmp" "$OUT"
      echo "$(date -u +%FT%TZ) capture OK -> $OUT" >> "$LOG"
      # Observability overhead gate: rerun bench_suite config 8 with
      # span recording on vs off and assert <5% per-gulp regression;
      # both runs land in BENCH_OBS_${ROUND}.json.  A failure exits
      # nonzero (the capture artifacts above are already in place).
      if [ "${BF_SKIP_OBS_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) observability overhead gate (config 8)" >> "$LOG"
        python tools/obs_overhead.py --out "BENCH_OBS_${ROUND}.json" >> "$LOG" 2>&1
        orc=$?
        echo "$(date -u +%FT%TZ) overhead gate rc=$orc" >> "$LOG"
        if [ "$orc" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) observability overhead gate FAILED" >> "$LOG"
          exit "$orc"
        fi
      fi
      # End-to-end observability gate: config 12 on the CPU backend —
      # the FULL stack (trace context + spans + SLO tracking) must stay
      # under the 5% overhead bar on the config-8 chain, the two-
      # pipeline loopback run must produce one MERGED cross-host trace,
      # and the sink pipeline must report a capture-to-commit p99.
      # Writes BENCH_E2E_${ROUND}.json.  A failure exits nonzero.
      if [ "${BF_SKIP_E2E_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) e2e observability gate (config 12)" >> "$LOG"
        E2E_OUT="BENCH_E2E_${ROUND}.json"
        # keep the previous round's artifact for the regression sentinel
        E2E_PREV=""
        if [ -f "$E2E_OUT" ]; then
          E2E_PREV="${E2E_OUT}.prev"
          cp "$E2E_OUT" "$E2E_PREV"
        elif [ -f "BENCH_E2E_cpu.json" ]; then
          E2E_PREV="BENCH_E2E_cpu.json"
        fi
        python tools/e2e_gate.py --out "$E2E_OUT" >> "$LOG" 2>&1
        erc=$?
        echo "$(date -u +%FT%TZ) e2e gate rc=$erc" >> "$LOG"
        if [ "$erc" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) e2e observability gate FAILED" >> "$LOG"
          exit "$erc"
        fi
        # Regression sentinel (ADVISORY): diff the fresh artifact
        # against the previous round's and log drifts beyond the
        # watchlist thresholds — the verdict is informational here
        # (tools/telemetry_diff.py --strict exists for CI that wants
        # a hard gate).
        if [ -n "$E2E_PREV" ]; then
          echo "$(date -u +%FT%TZ) telemetry drift sentinel vs $E2E_PREV (advisory)" >> "$LOG"
          python tools/telemetry_diff.py "$E2E_PREV" "$E2E_OUT" >> "$LOG" 2>&1 || true
          rm -f "${E2E_OUT}.prev"
        fi
      fi
      # Macro-gulp batch gate: config 9 on the CPU backend — K=16 must
      # not regress vs K=1 (min-of-N, alternating arm order) and the
      # dispatch amortization must actually engage.  A failure exits
      # nonzero (the capture artifacts above are already in place).
      if [ "${BF_SKIP_BATCH_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) macro-gulp batch gate (config 9, CPU)" >> "$LOG"
        python tools/batch_gate.py --out "BENCH_BATCH_${ROUND}.json" >> "$LOG" 2>&1
        grc=$?
        echo "$(date -u +%FT%TZ) batch gate rc=$grc" >> "$LOG"
        if [ "$grc" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) macro-gulp batch gate FAILED" >> "$LOG"
          exit "$grc"
        fi
      fi
      # Compiled-segment gate: config 16 on the CPU backend — the
      # segment compiler must fuse the unfused device chain into ONE
      # program (byte-identical outputs, zero member-block dispatches,
      # both interior rings elided) and must not regress vs the
      # hand-fused macro K=16 arm.  A failure exits nonzero (the
      # capture artifacts above are already in place).
      if [ "${BF_SKIP_SEGMENT_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) compiled-segment gate (config 16, CPU)" >> "$LOG"
        python tools/segment_gate.py --out "BENCH_SEGMENT_${ROUND}.json" >> "$LOG" 2>&1
        sgc=$?
        echo "$(date -u +%FT%TZ) segment gate rc=$sgc" >> "$LOG"
        if [ "$sgc" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) compiled-segment gate FAILED" >> "$LOG"
          exit "$sgc"
        fi
      fi
      # Auto-tune convergence gate: config 14 on the CPU backend — the
      # closed-loop controller must tune a de-tuned cold start (K=1,
      # sync=1) to within ~5% of the hand-tuned config-9 optimum with
      # byte-identical outputs, and the converged controller (no
      # retunes firing) must cost <2% on the hand-tuned arm.  The
      # converged knob values land in BENCH_TUNE_${ROUND}.json.  A
      # failure exits nonzero (the capture artifacts above are
      # already in place).
      if [ "${BF_SKIP_TUNE_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) auto-tune convergence gate (config 14, CPU)" >> "$LOG"
        python tools/autotune_gate.py --out "BENCH_TUNE_${ROUND}.json" >> "$LOG" 2>&1
        trc=$?
        echo "$(date -u +%FT%TZ) autotune gate rc=$trc" >> "$LOG"
        if [ "$trc" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) auto-tune convergence gate FAILED" >> "$LOG"
          exit "$trc"
        fi
      fi
      # Quantized-beamformer gate: config 13 on the CPU backend — the
      # measured quantized winner must beat the f32 baseline arm on
      # the end-to-end chain (min-of-N, alternating arms), stay inside
      # the declared accuracy class, and be run-to-run deterministic.
      # A failure exits nonzero (the capture artifacts above are
      # already in place).
      if [ "${BF_SKIP_BEAM_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) quantized beamformer gate (config 13, CPU)" >> "$LOG"
        python tools/beam_gate.py --out "BENCH_BEAM_${ROUND}.json" >> "$LOG" 2>&1
        bmrc=$?
        echo "$(date -u +%FT%TZ) beam gate rc=$bmrc" >> "$LOG"
        if [ "$bmrc" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) quantized beamformer gate FAILED" >> "$LOG"
          exit "$bmrc"
        fi
      fi
      # Ring-bridge wire gate: config 10 on the CPU backend — wire v2
      # (zero-copy, windowed) must not regress vs the naive v1 pump
      # and both arms must round-trip byte-identically.  A failure
      # exits nonzero (the capture artifacts above are already in
      # place).
      if [ "${BF_SKIP_BRIDGE_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) ring bridge wire gate (config 10, CPU)" >> "$LOG"
        python tools/bridge_gate.py --out "BENCH_BRIDGE_${ROUND}.json" >> "$LOG" 2>&1
        brg=$?
        echo "$(date -u +%FT%TZ) bridge gate rc=$brg" >> "$LOG"
        if [ "$brg" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) ring bridge wire gate FAILED" >> "$LOG"
          exit "$brg"
        fi
      fi
      # Chaos/soak gate: config 15 on CPU — a bridged two-process
      # pipeline under a scripted overload+kill+fault schedule must
      # never deadlock, account every lost byte in the shed ledgers
      # (no silent loss), traverse SHEDDING and recover to OK, and
      # keep the capture-to-exit p99 under BF_SLO_MS while shedding
      # (tools/chaos_gate.py; docs/robustness.md "Overload &
      # degradation").  Writes CHAOS_SOAK_${ROUND}.json.
      if [ "${BF_SKIP_CHAOS_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) chaos/soak gate (config 15, CPU)" >> "$LOG"
        python tools/chaos_gate.py --out "CHAOS_SOAK_${ROUND}.json" >> "$LOG" 2>&1
        crc_gate=$?
        echo "$(date -u +%FT%TZ) chaos gate rc=$crc_gate" >> "$LOG"
        if [ "$crc_gate" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) chaos/soak gate FAILED" >> "$LOG"
          exit "$crc_gate"
        fi
      fi
      # Fabric chaos gate: config 17 on CPU — a 4-process loopback
      # fabric (2 capture hosts fan-in to a reduce host, reduce
      # fans out to a leg through a chaos proxy) must survive a
      # SIGKILL'd capture host: survivors shed counted and recover
      # (SHEDDING -> OK), the relaunched host rejoins and replays
      # ONLY unacked frames (session adoption + resume probe), the
      # dead origin is marked gapped not stalled on, and produced ==
      # delivered + shed holds byte-exact across all surviving
      # ledgers (tools/fabric_gate.py; docs/fabric.md).  Writes
      # FABRIC_CHAOS_${ROUND}.json.
      if [ "${BF_SKIP_FABRIC_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) fabric chaos gate (config 17, CPU)" >> "$LOG"
        python tools/fabric_gate.py --out "FABRIC_CHAOS_${ROUND}.json" >> "$LOG" 2>&1
        frc_gate=$?
        echo "$(date -u +%FT%TZ) fabric gate rc=$frc_gate" >> "$LOG"
        if [ "$frc_gate" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) fabric chaos gate FAILED" >> "$LOG"
          exit "$frc_gate"
        fi
      fi
      # Multi-tenant service gate: config 18 on CPU — the JobManager
      # must run 3 concurrent tenant jobs with byte-correct outputs,
      # contain a BF_FAULTS-killed tenant (survivors DONE with health
      # OK, zero cross-tenant shed/poison), enforce the paced
      # per-tenant quotas within 10% of spec, and warm-start a
      # resubmitted topology >= 2x faster than cold with ZERO
      # recompiles (tools/service_gate.py; docs/service.md).  Writes
      # SERVICE_${ROUND}.json.
      if [ "${BF_SKIP_SERVICE_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) multi-tenant service gate (config 18, CPU)" >> "$LOG"
        python tools/service_gate.py --out "SERVICE_${ROUND}.json" >> "$LOG" 2>&1
        src_gate=$?
        echo "$(date -u +%FT%TZ) service gate rc=$src_gate" >> "$LOG"
        if [ "$src_gate" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) multi-tenant service gate FAILED" >> "$LOG"
          exit "$src_gate"
        fi
      fi
      # Elastic control-plane gate: config 20 on CPU — the scheduler
      # must pre-gate the cross-host placement (BF-E22x), detect a
      # SIGKILLed host, automatically re-place its tenant as a WARM
      # zero-recompile start resuming from the durable AckLedger
      # frontier (byte-exact, bounded counted loss), displace the
      # lowest-priority tenant on the oversubscribed survivor (shed
      # by policy, no deadlock), and restore an SLO violator through
      # the cross-tenant arbiter (tools/sched_gate.py;
      # docs/scheduler.md).  Writes SCHED_CHAOS_${ROUND}.json.
      if [ "${BF_SKIP_SCHED_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) elastic control-plane gate (config 20, CPU)" >> "$LOG"
        python tools/sched_gate.py --out "SCHED_CHAOS_${ROUND}.json" >> "$LOG" 2>&1
        sch_gate=$?
        echo "$(date -u +%FT%TZ) sched gate rc=$sch_gate" >> "$LOG"
        if [ "$sch_gate" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) elastic control-plane gate FAILED" >> "$LOG"
          exit "$sch_gate"
        fi
      fi
      # Fleet observability gate: config 21 on CPU — the streaming
      # telemetry plane must adopt both publishers, mark a SIGKILLed
      # host stale then DEAD (a never-seen host stays UNKNOWN), fire
      # and resolve the tenant-absence alert around the automatic
      # re-placement, archive a black-box bundle trace_merge consumes
      # directly, label the merged Prometheus export per host/tenant,
      # and keep streaming-publish overhead under 2% — also proven on
      # the config-8 chain by the obs_overhead fleet arm below
      # (tools/fleet_gate.py; docs/observability.md "Fleet plane").
      # Writes FLEET_OBS_${ROUND}.json + OBS_FLEET_${ROUND}.json.
      if [ "${BF_SKIP_FLEET_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) fleet observability gate (config 21, CPU)" >> "$LOG"
        python tools/fleet_gate.py --out "FLEET_OBS_${ROUND}.json" >> "$LOG" 2>&1
        flt_gate=$?
        echo "$(date -u +%FT%TZ) fleet gate rc=$flt_gate" >> "$LOG"
        if [ "$flt_gate" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) fleet observability gate FAILED" >> "$LOG"
          exit "$flt_gate"
        fi
        echo "$(date -u +%FT%TZ) fleet publish overhead arm (config-8 chain, CPU)" >> "$LOG"
        python tools/obs_overhead.py --stack fleet --reps 3 \
          --out "OBS_FLEET_${ROUND}.json" >> "$LOG" 2>&1
        flt_ovh=$?
        echo "$(date -u +%FT%TZ) fleet overhead rc=$flt_ovh" >> "$LOG"
        if [ "$flt_ovh" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) fleet publish overhead arm FAILED" >> "$LOG"
          exit "$flt_ovh"
        fi
      fi
      # Mesh-resident pipeline gate: config 11 on an 8-device
      # host-platform mesh — the sharded arm must match the
      # single-device arm, sharded spans must actually flow, and the
      # compiled mesh plans must be collective-free (zero reshards).
      # Writes MULTICHIP_${ROUND}.json (the revived multichip artifact
      # series).  A failure exits nonzero (the capture artifacts above
      # are already in place).
      if [ "${BF_SKIP_MESH_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) mesh pipeline gate (config 11, 8-dev host mesh)" >> "$LOG"
        python tools/mesh_gate.py --out "MULTICHIP_${ROUND}.json" >> "$LOG" 2>&1
        mrc=$?
        echo "$(date -u +%FT%TZ) mesh gate rc=$mrc" >> "$LOG"
        if [ "$mrc" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) mesh pipeline gate FAILED" >> "$LOG"
          exit "$mrc"
        fi
      fi
      # FX-correlator flagship gate: config 19 — quantized X-engine
      # winner must beat the complex64 baseline, every arm must be
      # byte-identical to the sequential oracle, and the fused
      # segment arm must dispatch its member blocks ZERO times.
      # Writes BENCH_FXCORR_${ROUND}.json plus the mesh-scaling row
      # MULTICHIP_${ROUND}_fxcorr.json.
      if [ "${BF_SKIP_FXCORR_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) fx correlator gate (config 19, 8-dev host mesh)" >> "$LOG"
        python tools/fxcorr_gate.py --out "BENCH_FXCORR_${ROUND}.json" \
          --mesh-out "MULTICHIP_${ROUND}_fxcorr.json" >> "$LOG" 2>&1
        xrc=$?
        echo "$(date -u +%FT%TZ) fxcorr gate rc=$xrc" >> "$LOG"
        if [ "$xrc" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) fx correlator gate FAILED" >> "$LOG"
          exit "$xrc"
        fi
      fi
      # FDMT FRB-search flagship gate: config 22 — all three arms
      # (unfused / halo-carried segment / segment at macro K) must be
      # byte-identical and match the float64 numpy oracle, the
      # ``overlap`` fusion boundary must be provably lifted (zero
      # member dispatches, zero interior-ring span traffic under
      # BF_RINGCHECK=1), and capture-to-candidate p99 must sit under
      # BF_SLO_MS.  Writes BENCH_FDMT_${ROUND}.json.
      if [ "${BF_SKIP_FDMT_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) fdmt frb-search gate (config 22)" >> "$LOG"
        python tools/fdmt_gate.py --out "BENCH_FDMT_${ROUND}.json" >> "$LOG" 2>&1
        frc=$?
        echo "$(date -u +%FT%TZ) fdmt gate rc=$frc" >> "$LOG"
        if [ "$frc" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) fdmt frb-search gate FAILED" >> "$LOG"
          exit "$frc"
        fi
      fi
      # Wire-rate capture flagship gate: config 23 — the sharded
      # zero-copy UDP engine must sustain its loopback rate ladder at
      # <1% loss with an exact loss ledger, ring contents byte-equal
      # to the blaster oracle, and a paired-median win over the
      # staged single-thread arm.  Writes BENCH_CAPTURE_${ROUND}.json.
      if [ "${BF_SKIP_CAPTURE_GATE:-0}" != "1" ]; then
        echo "$(date -u +%FT%TZ) wire-rate capture gate (config 23)" >> "$LOG"
        python tools/capture_gate.py --out "BENCH_CAPTURE_${ROUND}.json" >> "$LOG" 2>&1
        crc=$?
        echo "$(date -u +%FT%TZ) capture gate rc=$crc" >> "$LOG"
        if [ "$crc" -ne 0 ]; then
          echo "$(date -u +%FT%TZ) wire-rate capture gate FAILED" >> "$LOG"
          exit "$crc"
        fi
      fi
      exit 0
    fi
    # never leave a truncated artifact where round automation could
    # commit it as if it were real
    rm -f "$OUT.tmp"
    echo "$(date -u +%FT%TZ) bench attempt failed; continuing watch" >> "$LOG"
  fi
  sleep 240
done
echo "$(date -u +%FT%TZ) watcher exhausted retries" >> "$LOG"
exit 1
