#!/usr/bin/env python3
"""Fabric CLI: lint, launch, and inspect multi-host fabric specs
(bifrost_tpu.fabric; docs/fabric.md).

Subcommands::

    bf_fabric.py lint spec.json
        Statically verify the spec (analysis.verify.verify_fabric:
        BF-E200 endpoint mismatch, BF-E201 port collision, BF-W202
        window/stripe sizing, BF-W203 quota-vs-span) and print the
        report.  Exit codes match tools/bf_lint.py: 0 clean,
        3 errors found, 2 the spec could not be read.

    bf_fabric.py launch spec.json --host NAME --builder pkg.mod:fn
        Materialize and run NAME's sub-pipeline: the builder callable
        receives a FabricHostContext (ctx.source/ctx.sink wire the
        spec's links).  Runs until the stream completes or SIGTERM
        drains the fabric cleanly.  This is the per-host entry point
        a process supervisor (systemd, k8s) runs on each machine.

    bf_fabric.py up spec.json --builder pkg.mod:fn [--hosts a,b,...]
        Local loopback demo/drill: spawn every host of the spec (or a
        subset) as a subprocess of THIS machine running ``launch``,
        forward SIGINT/SIGTERM, and exit when all hosts do.  The
        builder must dispatch on ``ctx.host``.

    bf_fabric.py status
        One-shot fabric status from the local proclog tree: every
        launcher's ``fabric/health`` row (state, peers, end-to-end
        age p99), followed by the joined per-host × per-tenant
        rollup (``fabric/health`` + ``service/tenants`` +
        ``sched/placements`` merged into one table —
        ``bifrost_tpu.scheduler.joined_rollup``, the same table
        ``bf_sched.py status`` prints and like_top renders as
        ``[sched]``).

The builder spec ``pkg.mod:fn`` imports ``pkg.mod`` and calls ``fn``
with the context; relative module paths resolve from the CWD.
"""

import argparse
import importlib
import json
import os
import signal
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _load_spec(path):
    from bifrost_tpu.fabric import FabricSpec
    return FabricSpec.load(path)


def _load_builder(spec_str):
    mod_name, _, fn_name = spec_str.partition(':')
    if not fn_name:
        raise ValueError("--builder must be 'module:function'")
    sys.path.insert(0, os.getcwd())
    mod = importlib.import_module(mod_name)
    return getattr(mod, fn_name)


def cmd_lint(args):
    from bifrost_tpu.analysis.verify import (verify_fabric,
                                             format_report, errors)
    try:
        spec = _load_spec(args.spec)
    except (OSError, ValueError) as exc:
        print('bf_fabric: cannot read spec %s: %s' % (args.spec, exc))
        return 2
    diags = verify_fabric(spec)
    print('bf_fabric: fabric %r: %d host(s), %d link(s), '
          '%d diagnostic(s)' % (spec.name, len(spec.hosts),
                                len(spec.links), len(diags)))
    if diags:
        print(format_report(diags))
    else:
        print('  (clean)')
    return 3 if errors(diags) else 0


def cmd_launch(args):
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    from bifrost_tpu.fabric import FabricHost
    spec = _load_spec(args.spec)
    builder = _load_builder(args.builder)
    fh = FabricHost(spec, args.host, builder)
    fh.build()
    fh.run()
    state = fh.health()['state']
    print('bf_fabric: host %r finished in state %s'
          % (args.host, state))
    return 0 if state in ('OK', 'DEGRADED') else 3


def cmd_up(args):
    spec = _load_spec(args.spec)
    hosts = args.hosts.split(',') if args.hosts \
        else sorted(spec.hosts)
    procs = {}
    for host in hosts:
        procs[host] = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), 'launch',
             args.spec, '--host', host, '--builder', args.builder])

    def forward(signum, _frame):
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signum)
    signal.signal(signal.SIGINT, forward)
    signal.signal(signal.SIGTERM, forward)
    rc = 0
    for host, p in procs.items():
        p.wait()
        print('bf_fabric: host %r exited rc=%d' % (host, p.returncode))
        rc = rc or p.returncode
    return rc


def cmd_status(args):
    from bifrost_tpu import proclog
    from bifrost_tpu.monitor_utils import list_pipelines
    rows = 0
    for pid in list_pipelines():
        contents = proclog.load_by_pid(pid)
        row = contents.get('fabric', {}).get('health')
        if not row:
            continue
        rows += 1
        print('%-24s host %-12s role %-8s state %-9s peers %s/%s '
              'dead=%s%s'
              % (pid, row.get('host', '?'), row.get('role', '?'),
                 row.get('state', '?'), row.get('peers_alive', '?'),
                 row.get('peers_total', '?'),
                 row.get('peers_dead', 'none'),
                 ('  e2e_p99=%sms' % row['fabric_exit_age_p99_ms'])
                 if row.get('fabric_exit_age_p99_ms') not in
                 (None, '') else ''))
        member = contents.get('fabric', {}).get('membership')
        if member and args.verbose:
            peers = ['%s=%s' % (k[len('peer.'):], v)
                     for k, v in sorted(member.items())
                     if k.startswith('peer.')]
            if peers:
                print('  peers: %s' % '  '.join(peers))
    if not rows:
        print('bf_fabric: no fabric launchers found in the proclog '
              'tree (%s)' % proclog.proclog_dir())
    # joined host × tenant rollup: fabric/health + service/tenants +
    # sched/placements merged (docs/scheduler.md)
    from bifrost_tpu.scheduler import joined_rollup, format_rollup
    joined = joined_rollup()
    if any(r['tenants'] for r in joined):
        print('bf_fabric: host × tenant rollup:')
        print(format_rollup(joined))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest='cmd', required=True)
    p = sub.add_parser('lint', help='statically verify a fabric spec')
    p.add_argument('spec')
    p.set_defaults(fn=cmd_lint)
    p = sub.add_parser('launch', help="run one host's sub-pipeline")
    p.add_argument('spec')
    p.add_argument('--host', required=True)
    p.add_argument('--builder', required=True,
                   help="builder callable as 'module:function'")
    p.set_defaults(fn=cmd_launch)
    p = sub.add_parser('up', help='spawn every host locally (demo)')
    p.add_argument('spec')
    p.add_argument('--builder', required=True)
    p.add_argument('--hosts', default='',
                   help='comma-separated subset (default: all)')
    p.set_defaults(fn=cmd_up)
    p = sub.add_parser('status', help='fabric status from proclogs')
    p.add_argument('--verbose', action='store_true')
    p.set_defaults(fn=cmd_status)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == '__main__':
    sys.exit(main())
