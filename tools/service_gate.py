#!/usr/bin/env python3
"""Service-tier gate: the multi-tenant JobManager must hold its
isolation, quota, and warm-start invariants.

Runs bench_suite config 18 (bifrost_tpu.service — docs/service.md: 3
concurrent tenant jobs — serialized-recording replay at loop=3, flat
binary file ingest, and a paced synthetic capture — with paced
token-bucket quotas and one tenant killed mid-run by ``BF_FAULTS``)
in a fresh subprocess pinned to the CPU backend, and asserts:

- ``tenants_concurrent``       — the three jobs genuinely overlapped;
- ``outputs_byte_correct``     — replay and file tenants delivered
  byte-exact streams (replay: 3 identical renumbered loops), the
  killed tenant a clean prefix;
- ``fault_tenant_failed`` / ``fault_contained`` — the BF_FAULTS
  tenant FAILED while both survivors finished DONE with health OK;
- ``zero_cross_tenant_shed`` / ``zero_cross_tenant_poison`` — the
  blast radius stopped at the failed tenant's own rings: survivors
  show zero shed and zero poisoned rings;
- ``quota_within_10pct``       — both paced per-tenant quotas were
  enforced within 10% of spec;
- ``warm_speedup_ge2`` / ``warm_zero_recompiles`` /
  ``warm_profile_adopted`` — a resubmitted identical topology
  started >= 2x faster than its cold run with ZERO
  ``fused.plan_builds`` (plan-depot replay) and an adopted knob
  profile, byte-identical output;
- ``tenants_telemetry``        — ``telemetry.snapshot()['tenants']``
  carried every tenant's rollup.

The full config result is written to the ``--out`` JSON artifact
(``SERVICE_${ROUND}.json``) so bench rounds record the service tier's
health next to the throughput numbers.

Exit codes: 0 pass, 3 an invariant failed, 2 the drill failed to run.
``tools/watch_and_bench.sh`` runs this after the fabric gate
(``BF_SKIP_SERVICE_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_config18(timeout=900):
    """One bench_suite --config 18 subprocess on the CPU backend;
    returns its result dict."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    # configured fault/quota/tuning knobs would skew the scripted drill
    # BF_SEGMENTS would replace the warm chain's FusedBlocks with
    # fresh SegmentBlocks (no plan depot -> spurious recompiles) and
    # an ambient BF_COMPILE_CACHE would collapse the cold-start
    # latency the warm speedup is measured against
    for var in ('BF_FAULTS', 'BF_OVERLOAD_POLICY', 'BF_SLO_MS',
                'BF_AUTOTUNE', 'BF_SERVE_MAX_TENANTS',
                'BF_SERVE_WARM', 'BF_SERVE_QUOTA_BURST',
                'BF_GULP_BATCH', 'BF_SYNC_DEPTH', 'BF_SEGMENTS',
                'BF_COMPILE_CACHE'):
        env.pop(var, None)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
         '--config', '18'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and 'invariants' in d:
            return d
    raise RuntimeError(
        'config 18 produced no invariants result (rc=%d):\n%s\n%s'
        % (out.returncode, out.stdout[-1200:], out.stderr[-1200:]))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default='SERVICE_cpu.json',
                    help='artifact path for the full config result')
    ap.add_argument('--timeout', type=int, default=900)
    args = ap.parse_args(argv)
    if os.environ.get('BF_SKIP_SERVICE_GATE', '0') == '1':
        print('service_gate: skipped (BF_SKIP_SERVICE_GATE=1)')
        return 0
    try:
        res = run_config18(timeout=args.timeout)
    except Exception as exc:
        print('service_gate: drill failed to run: %s: %s'
              % (type(exc).__name__, exc))
        return 2
    res['round'] = os.environ.get('BF_BENCH_ROUND', '')
    with open(args.out, 'w') as f:
        json.dump(res, f, indent=2, sort_keys=True)
        f.write('\n')
    inv = res.get('invariants', {})
    for name in sorted(inv):
        print('%-26s %s' % (name, 'ok' if inv[name] else 'FAIL'))
    print('warm: %s' % json.dumps(res.get('warm', {}),
                                  sort_keys=True))
    print('quota err %%: %s' % json.dumps(
        res.get('quota_err_pct', {}), sort_keys=True))
    ok = bool(inv) and all(inv.values())
    print('service_gate: %s -> %s' % ('PASS' if ok else 'FAIL',
                                      args.out))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
