#!/usr/bin/env python3
"""Show hyperthread sibling groups (reference: tools/getsiblings).

Helps choose cores for capture threads that do not share an execution
unit with compute threads.
"""

import glob
import sys


def main():
    groups = {}
    for path in sorted(glob.glob(
            '/sys/devices/system/cpu/cpu*/topology/thread_siblings_list')):
        cpu = path.split('/')[5][3:]
        try:
            with open(path) as f:
                sibs = f.read().strip()
        except OSError:
            continue
        groups.setdefault(sibs, []).append(cpu)
    for sibs in sorted(groups, key=lambda s: int(s.split(',')[0].split('-')[0])):
        print(sibs)
    return 0


if __name__ == '__main__':
    sys.exit(main())
