#!/usr/bin/env python3
"""Inspect the measured-probe winner caches (``ops/mprobe.py``).

Selection is measured, then frozen to disk — which means a stale
winner (older package, different device kind) or a coin-flip ranking
that squeaked past the noise threshold silently shapes every later
session.  This tool makes the cache inspectable:

    python tools/mprobe_report.py                 # all families
    python tools/mprobe_report.py --family beamform
    python tools/mprobe_report.py --json          # machine-readable
    python tools/mprobe_report.py --clear         # drop winner caches

Per cached key it prints the winner, every candidate's best-of-N ms,
and the margin (runner-up / winner — values near 1.0 are coin flips
the persist policy should have re-measured; see mprobe.select's
``noise`` threshold).  Keys are prefixed with the backend tag they
were measured under, so a cache carried across device kinds is
immediately visible.

``--clear`` removes the family files (all of them, or just
``--family``); the next session re-measures.  Exit codes follow
tools/telemetry_diff.py: 0 = ok, 2 = cache dir unreadable.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def cache_dir():
    from bifrost_tpu.ops import mprobe
    return os.path.dirname(mprobe.cache_path('x'))


def _is_winner_cache(data):
    """BF_CACHE_DIR also holds non-mprobe state (telemetry_usage.json
    and friends): a file counts as a winner cache only when every
    entry is a {'winner': ...} dict — anything else is foreign and
    must be neither rendered as probes nor deleted by --clear."""
    return isinstance(data, dict) and data and all(
        isinstance(v, dict) and 'winner' in v for v in data.values())


def load_families(family=None):
    """{family: {key: entry}} from the on-disk winner caches.  Entries
    are the raw persisted dicts ({'winner': ..., 'ms': {...}});
    unreadable files surface as {'_error': ...} so a corrupt cache is
    reported, not skipped; foreign (non-mprobe) JSON files are
    skipped."""
    out = {}
    for path in sorted(glob.glob(os.path.join(cache_dir(), '*.json'))):
        name = os.path.splitext(os.path.basename(path))[0]
        if family and name != family:
            continue
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            out[name] = {'_error': '%s: %s' % (type(e).__name__, e)}
            continue
        if _is_winner_cache(data):
            out[name] = data
    return out


def in_process():
    """The CURRENT process's in-process cache (winner, ms, errors per
    key) — empty from the CLI (fresh interpreter), but callers
    embedding the report (tests, notebooks) see un-persisted entries:
    measurements whose candidates errored or ranked within noise."""
    from bifrost_tpu.ops import mprobe
    out = {}
    for name, fam in mprobe._cache.items():
        out[name] = {key: {'winner': w, 'ms': ms, 'errors': errs}
                     for key, (w, ms, errs) in fam.items()}
    return out


def margin(ms):
    """Runner-up-over-winner time ratio; None for a single candidate.
    1.0 = dead heat (a coin-flip winner), larger = decisive."""
    ranked = sorted(float(v) for v in ms.values())
    if len(ranked) < 2 or ranked[0] <= 0:
        return None
    return round(ranked[1] / ranked[0], 3)


def report(family=None):
    """Merged disk + in-process view, ready to render or JSON-dump."""
    fams = load_families(family)
    for name, entries in in_process().items():
        if family and name != family:
            continue
        dst = fams.setdefault(name, {})
        for key, entry in entries.items():
            merged = dict(entry)
            if key in dst:
                merged['persisted'] = True
            else:
                merged['persisted'] = False
            dst[key] = merged
    return fams


def render(fams):
    lines = []
    if not fams:
        lines.append('mprobe_report: no winner caches under %s'
                     % cache_dir())
        return lines
    for name in sorted(fams):
        entries = fams[name]
        lines.append('%s (%d key%s)' % (name, len(entries),
                                        '' if len(entries) == 1
                                        else 's'))
        if '_error' in entries:
            lines.append('  UNREADABLE: %s' % entries['_error'])
            continue
        for key in sorted(entries):
            e = entries[key]
            ms = e.get('ms', {}) or {}
            m = margin(ms)
            flags = []
            if m is not None and m < 1.10:
                flags.append('COIN-FLIP')
            if e.get('persisted') is False:
                flags.append('in-process only')
            if e.get('errors'):
                flags.append('errors: %s'
                             % ', '.join(sorted(e['errors'])))
            lines.append('  %s' % key)
            lines.append('    winner=%s  margin=%s%s'
                         % (e.get('winner'),
                            'n/a' if m is None else '%.3fx' % m,
                            ('  [%s]' % '; '.join(flags))
                            if flags else ''))
            for cand in sorted(ms, key=lambda c: float(ms[c])):
                lines.append('      %-14s %8.3f ms' % (cand,
                                                       float(ms[cand])))
    return lines


def clear(family=None):
    removed = []
    for path in sorted(glob.glob(os.path.join(cache_dir(), '*.json'))):
        name = os.path.splitext(os.path.basename(path))[0]
        if family and name != family:
            continue
        try:
            with open(path) as f:
                if not _is_winner_cache(json.load(f)):
                    continue           # foreign state: never delete
        except (OSError, ValueError):
            continue
        try:
            os.remove(path)
            removed.append(path)
        except OSError:
            pass
    from bifrost_tpu.ops import mprobe
    if family:
        mprobe._cache.pop(family, None)
    else:
        mprobe._cache.clear()
    return removed


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--family', default=None,
                    help='limit to one cache family (e.g. beamform, '
                         'linalg_xcorr, xengine, corner_turn, fdmt)')
    ap.add_argument('--json', action='store_true',
                    help='dump the merged report as JSON')
    ap.add_argument('--clear', action='store_true',
                    help='remove the winner cache file(s) so the next '
                         'session re-measures')
    args = ap.parse_args(argv)

    if args.clear:
        removed = clear(args.family)
        for path in removed:
            print('removed %s' % path)
        if not removed:
            print('mprobe_report: nothing to clear under %s'
                  % cache_dir())
        return 0

    if not os.path.isdir(cache_dir()):
        print('mprobe_report: no cache dir at %s' % cache_dir(),
              file=sys.stderr)
        return 2
    fams = report(args.family)
    if args.json:
        print(json.dumps(fams, indent=1, sort_keys=True))
    else:
        for line in render(fams):
            print(line)
    return 0


if __name__ == '__main__':
    sys.exit(main())
