#!/usr/bin/env python3
"""Wire-rate capture flagship gate: the sharded zero-copy UDP ingest
tier must sustain its packets/s ladder with an EXACT loss ledger and
byte-exact ring contents — this publishes the BENCH_CAPTURE_*.json
artifact series.

Runs bench_suite config 23 (bench_suite.bench_capture_wire_rate: a
paced loopback blaster drives a rate ladder into two paired arms —
the sharded zero-copy engine and the staged single-thread engine —
with alien/late packets injected mid-ladder) in a fresh subprocess
pinned to the CPU backend, and asserts:

- ``byte_identical``     — every ring cell equals the regenerated
  blaster oracle (zero-copy scatter is a data-path optimization,
  never a data change);
- ``ledger_exact``       — on every run of both arms,
  good + missing == the span grid and
  good == received - late - alien - dup - invalid (every received
  packet is accounted), with the injected alien count matched
  exactly;
- ``sustained_nonzero``  — each run held at least one rung under the
  loss ceiling (<1% by default);
- ``zero_copy_win``      — the zero-copy sharded arm's paired-median
  sustained pps beats the staged single-thread arm's.

Exit codes: 0 pass, 3 a gate condition failed, 2 the bench failed to
produce a result.  ``tools/watch_and_bench.sh`` runs this after the
FDMT gate (``BF_SKIP_CAPTURE_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_config23(timeout=1800):
    """One bench_suite --config 23 subprocess on the CPU backend;
    returns its result dict."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    # global capture knobs would skew the paired arm comparison — the
    # bench sets its own thread/vlen/zero-copy configuration
    env.pop('BF_CAPTURE_THREADS', None)
    env.pop('BF_CAPTURE_VLEN', None)
    env.pop('BF_CAPTURE_ZERO_COPY', None)
    env.pop('BF_NO_NATIVE_CAPTURE', None)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
         '--config', '23'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and 'capture' in d:
            return d
        if isinstance(d, dict) and d.get('error'):
            raise RuntimeError('config 23 failed: %s' % d['error'])
    raise RuntimeError(
        'config 23 produced no result (rc=%d):\n%s\n%s'
        % (out.returncode, out.stdout[-1000:], out.stderr[-1000:]))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    round_ = os.environ.get('BF_BENCH_ROUND', 'cpu')
    ap.add_argument('--out', default='BENCH_CAPTURE_%s.json' % round_,
                    help='artifact path (full config-23 result + '
                         'verdict)')
    ap.add_argument('--timeout', type=float, default=1800.0,
                    help='bench subprocess timeout in seconds')
    args = ap.parse_args()

    try:
        res = run_config23(timeout=args.timeout)
    except (RuntimeError, subprocess.TimeoutExpired) as exc:
        print('capture_gate: bench failed: %s' % exc, file=sys.stderr)
        return 2

    cap = res.get('capture', {})
    led = cap.get('ledger', {})
    byte_ok = bool(cap.get('byte_identical'))
    ledger_ok = bool(cap.get('all_runs_exact')) and \
        bool(led.get('alien_exact'))
    sustained_ok = int(cap.get('pps', 0)) > 0 and \
        int(cap.get('pps_staged_single', 0)) > 0
    win = float(cap.get('paired_median_win', 0.0))
    win_ok = win > 1.0
    ok = byte_ok and ledger_ok and sustained_ok and win_ok
    artifact = dict(res,
                    gate={'byte_identical': byte_ok,
                          'ledger_exact': ledger_ok,
                          'sustained_nonzero': sustained_ok,
                          'zero_copy_win': win_ok,
                          'pass': ok,
                          'round': os.environ.get('BF_BENCH_ROUND',
                                                  '')})
    with open(args.out, 'w') as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write('\n')
    print('capture_gate: %d pps / %.3f Gbit/s sustained at '
          'loss_frac=%s (staged single %d pps, paired-median win '
          '%.3f, %d zero-copy pkts), late=%s alien=%s '
          'byte_identical=%s ledger_exact=%s %s'
          % (cap.get('pps', -1), cap.get('gbps', -1),
             cap.get('loss_frac'), cap.get('pps_staged_single', -1),
             win, cap.get('zero_copy_pkts', -1), led.get('nlate'),
             led.get('nalien'), byte_ok, ledger_ok,
             'PASS' if ok else 'FAIL'))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
