#!/usr/bin/env python3
"""Observability overhead gate: span tracing must stay cheap.

Runs bench_suite config 8 (the async-transfer gulp loop — the hottest
host-side path in the framework) in fresh subprocesses, ``--reps``
interleaved repetitions per arm: span recording OFF (the default) vs
ON (``BF_TRACE_FILE`` set), then asserts the traced arm's best
per-gulp time regressed by less than ``--threshold`` percent (default
5).  Two noise defenses, both necessary in practice: the arms compare
per-arm MINIMA (run-to-run spread on a busy host is 2x — far larger
than the real instrumentation cost, which microbenchmarks at ~1us per
span), and the arm ORDER alternates between repetitions (a fixed
base-first order phase-locks against slow machine-state drift —
CPU-frequency / allocator / page-cache cycles — and measured a
spurious 80% "overhead" that vanished under interleaving).  Every
sample plus the verdict is written to the ``--out`` JSON artifact so
bench rounds record the observability cost next to the throughput
numbers.

Exit codes: 0 pass, 3 overhead above threshold, 2 a bench arm failed
to produce a result.  ``tools/watch_and_bench.sh`` runs this after a
successful bench capture (``BF_SKIP_OBS_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: per-gulp metric the gate compares (bench_xfer_overlap output)
METRIC = 'async_ms_per_gulp'

#: per-gulp metric of the ringcheck arm (the timed config-8 chain —
#: the ring-protocol checker's seams live on the ring span path, which
#: bench_xfer_overlap's raw engine loop never touches)
CHAIN_METRIC = 'chain_ms_per_gulp'

_CHAIN_SNIPPET = (
    "import json, sys; sys.path.insert(0, %r); "
    "from bench_suite import _timed_config8_chain as t; "
    "from bifrost_tpu.telemetry import counters, fleet; "
    "pub = fleet.acquire_publisher(); "
    "n = %%d; dt = t(ngulp=n); "
    "fleet.release_publisher(pub) if pub else None; "
    "print(json.dumps({'chain_ms_per_gulp': dt / n * 1e3, "
    "'wall_s': dt, "
    "'tuner_cpu_us': counters.get('autotune.tick_busy_us'), "
    "'fleet_pub_cpu_us': counters.get('fleet.pub.busy_us')}))"
    % ROOT)


def run_chain(armed, timeout=1800, stack='ringcheck',
              collector_port=None):
    """One timed config-8 chain run through a REAL pipeline
    (bench_suite._timed_config8_chain) with the stack under test
    armed or not — the measurement arm for ``--stack ringcheck``,
    ``--stack autotune`` and ``--stack fleet``.  The autotune arm runs
    the closed-loop controller with every knob ceiling pinned at the
    chain's current configuration (no retune can fire): the pure
    converged-controller cost the <2% acceptance bound in
    docs/autotune.md refers to, measured in fresh subprocesses where
    nothing else perturbs the arms.  The fleet arm streams the
    subprocess's telemetry to ``collector_port`` (an in-process
    FleetCollector in THIS process) at a 4Hz publish interval — the
    streaming-publish bound of docs/observability.md "Fleet plane"."""
    env = dict(os.environ)
    for knob in ('BF_TRACE_FILE', 'BF_TRACE', 'BF_WATCHDOG_SECS',
                 'BF_WATCHDOG_ESCALATE', 'BF_METRICS_FILE',
                 'BF_SLO_MS', 'BF_JAX_PROFILE', 'BF_RINGCHECK',
                 'BF_AUTOTUNE', 'BF_AUTOTUNE_PROFILE',
                 'BF_AUTOTUNE_INTERVAL', 'BF_AUTOTUNE_COOLDOWN',
                 'BF_AUTOTUNE_MIN_GAIN', 'BF_AUTOTUNE_MAX_BATCH',
                 'BF_AUTOTUNE_MAX_DEPTH', 'BF_AUTOTUNE_MAX_WINDOW',
                 'BF_AUTOTUNE_MAX_RING_BYTES', 'BF_FLEET_COLLECTOR',
                 'BF_FLEET_INTERVAL', 'BF_FLEET_HOST',
                 'BF_FLEET_FULL_EVERY'):
        env.pop(knob, None)
    if armed and stack == 'ringcheck':
        env['BF_RINGCHECK'] = '1'
    elif armed and stack == 'fleet':
        env['BF_FLEET_COLLECTOR'] = '127.0.0.1:%d' % collector_port
        env['BF_FLEET_INTERVAL'] = '0.25'
        env['BF_FLEET_HOST'] = 'obsgate'
    elif armed:
        # ceilings pinned at the chain's own config (K=1,
        # sync_depth=4): every step() returns None, so each knob
        # converges without a retune and the controller idles at the
        # deployment-default tick — pure converged overhead
        env['BF_AUTOTUNE'] = '1'
        env['BF_AUTOTUNE_MAX_BATCH'] = '1'
        env['BF_AUTOTUNE_MAX_DEPTH'] = '4'
        env['BF_AUTOTUNE_MAX_RING_BYTES'] = '1'
        env['BF_AUTOTUNE_PROFILE'] = os.path.join(
            tempfile.mkdtemp(prefix='bf_tune_gate_'), 'unused.json')
    # the autotune arm measures a FIXED per-run cost (controller
    # start/stop + the final telemetry pass, ~tens of ms) on top of a
    # negligible steady-state cost (a tick microbenchmarks at
    # ~0.3ms against a 0.5s interval): a long chain amortizes the
    # fixed part the way a real long-lived deployment does AND
    # shrinks the chain's per-run scheduling jitter below the 2%
    # bound (+-1% at this length, vs +-4% at 48 gulps), so the gate
    # judges the steady state rather than the thread setup or the
    # host's mood
    ngulp = 1920 if stack in ('autotune', 'fleet') else 48
    out = subprocess.run([sys.executable, '-c',
                          _CHAIN_SNIPPET % ngulp],
                         capture_output=True, text=True, env=env,
                         cwd=ROOT, timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and CHAIN_METRIC in d:
            return d
    raise RuntimeError(
        'timed chain produced no %s result (rc=%d):\n%s\n%s'
        % (CHAIN_METRIC, out.returncode, out.stdout[-1000:],
           out.stderr[-1000:]))


def run_config8(trace_file=None, timeout=1800, full_stack=False):
    """One bench_suite --config 8 subprocess; returns its result dict.
    ``trace_file`` set -> span recording on (plus the export cost);
    ``full_stack`` additionally arms trace-context stamping and
    BF_SLO_MS budget tracking on the traced arm (and explicitly
    disables the context on the baseline arm, since stamping defaults
    on) — the ``--stack full`` mode."""
    env = dict(os.environ)
    # strip EVERY knob that toggles span recording or adds publisher
    # work, so the baseline arm is genuinely instrumentation-off (an
    # inherited BF_WATCHDOG_SECS would arm the flight recorder and
    # make the gate compare on-vs-on)
    for knob in ('BF_TRACE_FILE', 'BF_TRACE', 'BF_WATCHDOG_SECS',
                 'BF_WATCHDOG_ESCALATE', 'BF_METRICS_FILE',
                 'BF_SLO_MS', 'BF_TRACE_CONTEXT', 'BF_JAX_PROFILE'):
        env.pop(knob, None)
    if trace_file is not None:
        env['BF_TRACE_FILE'] = trace_file
        if full_stack:
            env['BF_TRACE_CONTEXT'] = '1'
            env['BF_SLO_MS'] = '10000'
    elif full_stack:
        env['BF_TRACE_CONTEXT'] = '0'
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
         '--config', '8'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and METRIC in d:
            return d
    raise RuntimeError(
        'config 8 produced no %s result (rc=%d):\n%s\n%s'
        % (METRIC, out.returncode, out.stdout[-1000:],
           out.stderr[-1000:]))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default='BENCH_OBS.json',
                    help='artifact path (all samples + verdict)')
    ap.add_argument('--threshold', type=float, default=None,
                    help='max allowed regression in percent (default '
                         '5; --stack ringcheck defaults to 50 — a '
                         'debug tool gets a generous, but still '
                         'measured and recorded, bound)')
    ap.add_argument('--reps', type=int, default=4,
                    help='interleaved repetitions per arm '
                         '(minima are compared; order alternates)')
    ap.add_argument('--timeout', type=float, default=1800.0,
                    help='per-run bench timeout in seconds')
    ap.add_argument('--stack', choices=('spans', 'full', 'ringcheck',
                                        'autotune', 'fleet'),
                    default='spans',
                    help="what the traced arm enables: 'spans' (the "
                         "classic PR-3 gate), 'full' (spans + "
                         "trace-context stamping + BF_SLO_MS "
                         "tracking; baseline arm runs "
                         "BF_TRACE_CONTEXT=0), 'ringcheck' (the "
                         "dynamic ring-protocol checker BF_RINGCHECK=1 "
                         "on the timed config-8 PIPELINE chain, whose "
                         "ring spans are where the checker's seams "
                         "live — docs/analysis.md), or 'autotune' "
                         "(the closed-loop controller with every "
                         "knob ceiling pinned on the same chain — "
                         "the converged-controller bound of "
                         "docs/autotune.md, default threshold 2), or "
                         "'fleet' (streaming telemetry publisher "
                         "pushing 4Hz snapshots to an in-process "
                         "collector on the same chain — the <2% "
                         "streaming-publish bound of "
                         "docs/observability.md).  The chain-level "
                         "full-stack bar lives in tools/e2e_gate.py; "
                         "'spans'/'full' bound the same knobs on the "
                         "config-8 transfer loop.")
    args = ap.parse_args()
    if args.threshold is None:
        args.threshold = {'ringcheck': 50.0,
                          'autotune': 2.0,
                          'fleet': 2.0}.get(args.stack, 5.0)

    trace_tmp = os.path.join(tempfile.mkdtemp(prefix='bf_obs_gate_'),
                             'trace.json')
    full = args.stack == 'full'
    chain = args.stack in ('ringcheck', 'autotune', 'fleet')
    metric = CHAIN_METRIC if chain else METRIC
    collector = None
    if args.stack == 'fleet':
        # the receiving end lives HERE: the armed subprocess streams
        # to this collector, so the gate also proves the datagrams
        # actually arrive (fleet.msgs_rx below) rather than timing a
        # publisher shouting into a closed port
        sys.path.insert(0, ROOT)
        from bifrost_tpu.telemetry import fleet as _fleet
        collector = _fleet.FleetCollector(rules=[], interval=0.25)
        collector.start()
    base_runs, traced_runs = [], []
    try:
        for rep in range(max(args.reps, 1)):
            order = [(base_runs, False), (traced_runs, True)]
            if rep % 2:
                order.reverse()
            for runs, armed in order:
                if chain:
                    runs.append(run_chain(
                        armed, timeout=args.timeout, stack=args.stack,
                        collector_port=collector.port
                        if collector else None))
                else:
                    runs.append(run_config8(
                        trace_tmp if armed else None,
                        timeout=args.timeout, full_stack=full))
    except (RuntimeError, subprocess.TimeoutExpired) as exc:
        print('obs_overhead: bench arm failed: %s' % exc,
              file=sys.stderr)
        return 2
    finally:
        msgs_rx = 0
        if collector is not None:
            from bifrost_tpu.telemetry import counters as _counters
            msgs_rx = _counters.get('fleet.msgs_rx')
            collector.stop()
    if args.stack == 'fleet' and not msgs_rx:
        print('obs_overhead: fleet arm streamed no telemetry to the '
              'collector (fleet.msgs_rx == 0)', file=sys.stderr)
        return 2

    b = min(float(r[metric]) for r in base_runs)
    t = min(float(r[metric]) for r in traced_runs)
    ab_pct = None
    if args.stack in ('autotune', 'fleet'):
        # the BINDING number is the stack's directly-metered busy
        # time (autotune.tick_busy_us / fleet.pub.busy_us — a
        # conservative upper bound including the background thread's
        # own GIL waits) as a fraction of the pipeline wall:
        # deterministic to well under the 2% bound.
        # An A/B wall-clock comparison cannot certify 2% on a shared
        # CI host — adjacent same-length runs here spread by +-10%
        # under contention — so the drift-robust paired median of the
        # arms is recorded as a cross-check, not the verdict
        ratios = sorted(float(t_[metric]) / float(b_[metric])
                        for b_, t_ in zip(base_runs, traced_runs))
        ab_pct = (ratios[len(ratios) // 2] - 1.0) * 100.0
        cpu_key = 'tuner_cpu_us' if args.stack == 'autotune' \
            else 'fleet_pub_cpu_us'
        cpu = max(float(r.get(cpu_key) or 0)
                  for r in traced_runs) / 1e6
        wall = min(float(r.get('wall_s') or 0)
                   for r in traced_runs)
        overhead_pct = cpu / wall * 100.0 if wall > 0 else 0.0
    else:
        overhead_pct = (t / b - 1.0) * 100.0 if b > 0 else 0.0
    ok = overhead_pct < args.threshold
    artifact = {
        'metric': metric,
        'stack': args.stack,
        'reps': len(base_runs),
        'spans_disabled_ms': [float(r[metric]) for r in base_runs],
        'spans_enabled_ms': [float(r[metric]) for r in traced_runs],
        'spans_disabled': base_runs[-1],
        'spans_enabled': traced_runs[-1],
        'min_disabled_ms': b,
        'min_enabled_ms': t,
        'overhead_pct': round(overhead_pct, 2),
        'ab_paired_median_pct': (round(ab_pct, 2)
                                 if ab_pct is not None else None),
        'threshold_pct': args.threshold,
        'pass': ok,
        'round': os.environ.get('BF_BENCH_ROUND', ''),
        'trace_events_written': os.path.exists(trace_tmp),
    }
    if args.stack == 'fleet':
        artifact['fleet_msgs_rx'] = msgs_rx
    with open(args.out, 'w') as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write('\n')
    extra = ('' if ab_pct is None
             else ' [metered CPU; A/B paired median %+.2f%%]'
             % ab_pct)
    print('obs_overhead: %s min-of-%d: %.3fms off / %.3fms on -> '
          '%+.2f%% (threshold %.1f%%)%s %s'
          % (metric, len(base_runs), b, t, overhead_pct,
             args.threshold, extra, 'PASS' if ok else 'FAIL'))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
