#!/usr/bin/env python3
"""End-to-end observability gate: the FULL distributed-observability
stack (trace context + span recording/export + capture-to-commit SLO
tracking) must stay under the overhead bar, and the cross-host
machinery must actually work.

Runs bench_suite config 12 (bench_e2e_observability) in a fresh
subprocess pinned to the CPU backend and asserts:

- ``overhead_ok``   — the full-stack overhead on the config-8 fused
  chain is under ``--threshold`` percent (default 5).  The judged
  number is the MEDIAN OF PER-REP PAIRED RATIOS (each rep runs both
  arms back to back, so the ratio cancels the slow machine-state
  drift that dominates run-to-run spread on shared hosts); the
  classic min-of-N ratio and the baseline arm's spread are recorded
  in the artifact for context.
- ``merged_trace_ok`` — the two-pipeline loopback bridge run produced
  one merged Chrome trace (tools/trace_merge.py) where at least one
  (trace id, seq, gulp) identity appears on BOTH hosts' timelines.
- ``slo_tracked``   — the sink pipeline's ``telemetry.snapshot()``
  reported a capture-to-commit p99 (the ``slo.exit_age_s`` histogram
  is populated).

The full config result lands in the ``--out`` JSON artifact
(``BENCH_E2E_${ROUND}.json`` from the watcher).

Exit codes: 0 pass, 3 a gate condition failed, 2 the bench arm failed
to produce a result.  ``tools/watch_and_bench.sh`` runs this after the
observability gate (``BF_SKIP_E2E_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_config12(timeout=1800):
    """One bench_suite --config 12 subprocess on the CPU backend;
    returns its result dict."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    # a configured observability environment would contaminate the
    # arms (the config manages these knobs itself)
    for var in ('BF_TRACE_FILE', 'BF_TRACE', 'BF_TRACE_CONTEXT',
                'BF_SLO_MS', 'BF_METRICS_FILE', 'BF_WATCHDOG_SECS',
                'BF_JAX_PROFILE'):
        env.pop(var, None)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
         '--config', '12'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and 'overhead' in d:
            return d
    raise RuntimeError(
        'config 12 produced no overhead result (rc=%d):\n%s\n%s'
        % (out.returncode, out.stdout[-1000:], out.stderr[-1000:]))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default='BENCH_E2E.json',
                    help='artifact path (full config-12 result + '
                         'verdict)')
    ap.add_argument('--threshold', type=float, default=5.0,
                    help='max allowed full-stack overhead in percent '
                         '(paired-median estimator)')
    ap.add_argument('--timeout', type=float, default=1800.0,
                    help='bench subprocess timeout in seconds')
    args = ap.parse_args()

    try:
        res = run_config12(timeout=args.timeout)
    except (RuntimeError, subprocess.TimeoutExpired) as exc:
        print('e2e_gate: bench arm failed: %s' % exc, file=sys.stderr)
        return 2

    ov = res['overhead']
    overhead_pct = float(ov.get('overhead_pct', 0.0))
    overhead_ok = overhead_pct < args.threshold
    merged_ok = bool(res.get('merged_trace_ok'))
    slo_ok = bool(res.get('slo_tracked'))
    ok = overhead_ok and merged_ok and slo_ok
    artifact = dict(res,
                    gate={'overhead_pct': round(overhead_pct, 2),
                          'min_ratio_pct': ov.get('min_ratio_pct'),
                          'off_arm_spread_pct':
                              ov.get('off_arm_spread_pct'),
                          'threshold_pct': args.threshold,
                          'overhead_ok': overhead_ok,
                          'merged_trace_ok': merged_ok,
                          'slo_tracked': slo_ok,
                          'pass': ok,
                          'round': os.environ.get('BF_BENCH_ROUND',
                                                  '')})
    with open(args.out, 'w') as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write('\n')
    two_host = res.get('two_host', {})
    print('e2e_gate: full-stack overhead %+.2f%% paired-median '
          '(min-ratio %+.2f%%, off-arm spread %.1f%%, threshold '
          '%.1f%%), merged_trace=%s (%d shared identities), '
          'slo p99=%.2fms %s'
          % (overhead_pct, float(ov.get('min_ratio_pct', 0.0)),
             float(ov.get('off_arm_spread_pct', 0.0)),
             args.threshold, merged_ok,
             int(two_host.get('shared_identities', 0)),
             float(res.get('value', 0.0)),
             'PASS' if ok else 'FAIL'))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
