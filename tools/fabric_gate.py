#!/usr/bin/env python3
"""Fabric chaos gate: the multi-host fabric must survive a SIGKILL'd
host with byte-exact loss accounting.

Runs bench_suite config 17 (docs/fabric.md): a loopback fabric of four
launcher processes — two capture hosts fan-in to one reduce host,
which fans out through a chaos TCP proxy to one leg host — driven
through an overload pause, a SIGKILL of a capture host, and a jittered
rejoin.  Asserts the invariants:

- ``no_deadlock``             — every launcher exited cleanly;
- ``no_silent_loss``          — produced == delivered + shed,
  byte-exact across all SURVIVING ledgers (the killed host's journal
  is durable, so the audit covers the kill);
- ``exactly_once``            — per-origin delivery has no duplicates
  and preserves order (the rejoin replayed ONLY unacked frames);
- ``shedding_engaged`` / ``health_traversal`` — the pause forced
  counted shedding and reduce traversed SHEDDING -> OK;
- ``host_death_observed``     — membership saw the killed host
  alive -> dead -> alive;
- ``rejoin_replayed_only_unacked`` — the relaunched host resumed from
  the receiver's committed frontier through session adoption;
- ``origin_gapped_not_stalled`` — the fan-in marked the dead origin
  GAPPED via the ``_overload`` stamp instead of stalling the merge;
- ``fabric_slo_measured``     — the cross-host capture-to-sink age
  histogram recorded at the leg.

The full config result is written to the ``--out`` JSON artifact
(``FABRIC_CHAOS_${ROUND}.json``).  Exit codes: 0 pass, 3 an invariant
failed, 2 the drill failed to run.  ``tools/watch_and_bench.sh`` runs
this after the chaos gate (``BF_SKIP_FABRIC_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_config17(timeout=900):
    """One bench_suite --config 17 subprocess on the CPU backend;
    returns its result dict."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    # configured overload/fabric tuning would skew the scripted drill
    for var in ('BF_OVERLOAD_POLICY', 'BF_FAULTS', 'BF_SLO_MS',
                'BF_BRIDGE_WINDOW', 'BF_BRIDGE_STREAMS',
                'BF_FABRIC_STATE', 'BF_FABRIC_IDENTITY',
                'BF_FABRIC_HEARTBEAT_SECS', 'BF_FABRIC_DEADLINE_SECS',
                'BF_FABRIC_GAP_SECS', 'BF_FABRIC_REJOIN_CAP'):
        env.pop(var, None)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
         '--config', '17'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and 'invariants' in d:
            return d
    raise RuntimeError(
        'config 17 produced no invariants result (rc=%d):\n%s\n%s'
        % (out.returncode, out.stdout[-1200:], out.stderr[-1200:]))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default='FABRIC_CHAOS.json',
                    help='artifact path for the full config result')
    ap.add_argument('--timeout', type=int, default=900)
    args = ap.parse_args(argv)
    try:
        res = run_config17(timeout=args.timeout)
    except Exception as exc:
        print('fabric_gate: drill failed to run: %s: %s'
              % (type(exc).__name__, exc))
        return 2
    with open(args.out, 'w') as f:
        json.dump(res, f, indent=2, sort_keys=True)
    inv = res.get('invariants', {})
    for name in sorted(inv):
        print('%-28s %s' % (name, 'ok' if inv[name] else 'FAIL'))
    print('ledger: %s' % json.dumps(res.get('ledger', {}),
                                    sort_keys=True))
    ok = bool(inv) and all(inv.values())
    print('fabric_gate: %s -> %s' % ('PASS' if ok else 'FAIL',
                                     args.out))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
