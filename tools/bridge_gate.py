#!/usr/bin/env python3
"""Ring-bridge wire gate: wire v2 must not be slower than the naive v1
pump, and both arms must move bytes losslessly.

Runs bench_suite config 10 (loopback ring->TCP->ring pump: the seed
implementation's copying v1 sender/receiver vs the zero-copy windowed
v2 wire — bench_suite.bench_bridge) in a fresh subprocess pinned to
the CPU backend, and asserts:

- ``throughput_ok``     — the v2 arm's min-of-N wall time is not worse
  than naive v1's by more than ``--threshold`` percent (default 0: the
  pipelined wire must never cost throughput; the acceptance target for
  this machine class is >= 2x the naive arm, recorded in the artifact);
- ``outputs_identical`` — every received span in BOTH arms memcmp'd
  equal to the source gulp (a faster wire that corrupts or drops data
  must fail the gate, not pass silently).

The arm interleaving / min-of-N noise defenses live inside config 10
itself (same policy as the observability and batch gates: per-arm
minima, alternating arm order between repetitions).  The full config
result is written to the ``--out`` JSON artifact so bench rounds record
the bridge path's health next to the throughput numbers.

Exit codes: 0 pass, 3 a gate condition failed, 2 the bench arm failed
to produce a result.  ``tools/watch_and_bench.sh`` runs this after the
batch gate (``BF_SKIP_BRIDGE_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_config10(timeout=1800):
    """One bench_suite --config 10 subprocess on the CPU backend;
    returns its result dict."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    # configured bridge tuning would skew the fixed-arm comparison
    for var in ('BF_BRIDGE_STREAMS', 'BF_BRIDGE_WINDOW',
                'BF_BRIDGE_CRC'):
        env.pop(var, None)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
         '--config', '10'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and 'arms' in d:
            return d
    raise RuntimeError(
        'config 10 produced no arms result (rc=%d):\n%s\n%s'
        % (out.returncode, out.stdout[-1000:], out.stderr[-1000:]))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default='BENCH_BRIDGE.json',
                    help='artifact path (full config-10 result + '
                         'verdict)')
    ap.add_argument('--threshold', type=float, default=0.0,
                    help='max allowed v2 throughput regression vs '
                         'naive v1, percent (default 0: v2 >= v1)')
    ap.add_argument('--timeout', type=float, default=1800.0,
                    help='bench subprocess timeout in seconds')
    args = ap.parse_args()

    try:
        res = run_config10(timeout=args.timeout)
    except (RuntimeError, subprocess.TimeoutExpired) as exc:
        print('bridge_gate: bench arm failed: %s' % exc,
              file=sys.stderr)
        return 2

    t1 = float(res['arms']['v1_naive']['ms_min'])
    t2 = float(res['arms']['v2']['ms_min'])
    regression_pct = (t2 / t1 - 1.0) * 100.0 if t1 > 0 else 0.0
    throughput_ok = regression_pct <= args.threshold
    outputs_ok = bool(res.get('outputs_identical'))
    ok = throughput_ok and outputs_ok
    artifact = dict(res,
                    gate={'regression_pct': round(regression_pct, 2),
                          'threshold_pct': args.threshold,
                          'throughput_ok': throughput_ok,
                          'outputs_identical': outputs_ok,
                          'pass': ok,
                          'round': os.environ.get('BF_BENCH_ROUND',
                                                  '')})
    with open(args.out, 'w') as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write('\n')
    print('bridge_gate: v1 %.1fms / v2 %.1fms -> %.2fx '
          '(threshold %.1f%%), outputs_identical=%s %s'
          % (t1, t2, t1 / t2 if t2 > 0 else 0.0, args.threshold,
             outputs_ok, 'PASS' if ok else 'FAIL'))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
