#!/usr/bin/env python3
"""Fleet observability gate: the streaming telemetry plane must see a
host die, alert on it, archive the black box, and watch the fleet
heal — all from one collector.

Runs bench_suite config 21 (bifrost_tpu.telemetry.fleet —
docs/observability.md "Fleet plane": a 3-host fabric whose hostA is
a REAL subprocess streaming snapshot deltas to the head's
FleetCollector, SIGKILLed mid-stream) in a fresh subprocess pinned
to the CPU backend, and asserts:

- ``hosts_adopted``             — both publishers were adopted and
  the victim tenant was visible in the rollup before the fault;
- ``host_marked_stale``         — the silenced host crossed the
  collector's staleness deadline;
- ``host_dead_verdict``         — the attached Membership's verdict
  promoted stale to DEAD;
- ``unknown_not_dead``          — a rule watching a never-seen host
  stayed 'unknown' and never fired (unknown is not dead);
- ``absence_alert_fired_then_resolved`` — the tenant-absence rule
  FIRED after the kill and RESOLVED once the re-placed tenant
  re-surfaced on the survivor's stream;
- ``replacement_automatic``     — the scheduler's death watch moved
  the tenant to the survivor and it ran to DONE;
- ``incident_bundle_complete``  — the black-box bundle carries the
  dead host's flight record, last snapshots, wall-clock span origin,
  and (post settle) the scheduler's replacement record;
- ``trace_merge_consumes_bundle`` — ``tools/trace_merge.py`` merged
  the bundle directly, wall-aligning per-host timelines;
- ``merged_prom_labels``        — the merged Prometheus export
  carries per-host and per-tenant labels;
- ``publish_overhead_lt_2pct``  — the survivor publisher's metered
  busy time stayed under 2% of the streamed interval;
- ``counters_match_timeline``   — ``fleet.hosts_live``,
  ``alerts.fired/resolved``, ``incident.bundles`` and
  ``fleet.hosts_dead`` match the scripted fault timeline.

The full config result is written to the ``--out`` JSON artifact
(``FLEET_OBS_${ROUND}.json``) so bench rounds record the
observability plane's health next to the throughput numbers.

Exit codes: 0 pass, 3 an invariant failed, 2 the drill failed to
run.  ``tools/watch_and_bench.sh`` runs this after the scheduler
gate (``BF_SKIP_FLEET_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_config21(timeout=900):
    """One bench_suite --config 21 subprocess on the CPU backend;
    returns its result dict."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    # configured fault/quota/alert knobs would skew the scripted
    # drill; ambient fleet/fabric endpoints would leak a foreign
    # collector or spec into the drill's own plane
    for var in ('BF_FAULTS', 'BF_OVERLOAD_POLICY', 'BF_SLO_MS',
                'BF_AUTOTUNE', 'BF_SERVE_MAX_TENANTS',
                'BF_SERVE_WARM', 'BF_GULP_BATCH', 'BF_SYNC_DEPTH',
                'BF_SEGMENTS', 'BF_FABRIC_STATE',
                'BF_FABRIC_IDENTITY', 'BF_FABRIC_HEARTBEAT_SECS',
                'BF_FABRIC_DEADLINE_SECS',
                'BF_FLEET_COLLECTOR', 'BF_FLEET_HOST',
                'BF_FLEET_INTERVAL', 'BF_FLEET_FULL_EVERY',
                'BF_FLEET_DEADLINE', 'BF_FLEET_ROLLUP_FILE',
                'BF_FLEET_PROM_FILE', 'BF_FLEET_INCIDENT_DIR',
                'BF_FLEET_INCIDENT_COOLDOWN', 'BF_FLEET_SETTLE',
                'BF_ALERT_RULES', 'BF_ALERT_LOG',
                'BF_ALERT_WEBHOOK'):
        env.pop(var, None)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
         '--config', '21'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and 'invariants' in d:
            return d
    raise RuntimeError(
        'config 21 produced no invariants result (rc=%d):\n%s\n%s'
        % (out.returncode, out.stdout[-1200:], out.stderr[-1200:]))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default='FLEET_OBS_cpu.json',
                    help='artifact path for the full config result')
    ap.add_argument('--timeout', type=int, default=900)
    args = ap.parse_args(argv)
    if os.environ.get('BF_SKIP_FLEET_GATE', '0') == '1':
        print('fleet_gate: skipped (BF_SKIP_FLEET_GATE=1)')
        return 0
    try:
        res = run_config21(timeout=args.timeout)
    except Exception as exc:
        print('fleet_gate: drill failed to run: %s: %s'
              % (type(exc).__name__, exc))
        return 2
    res['round'] = os.environ.get('BF_BENCH_ROUND', '')
    with open(args.out, 'w') as f:
        json.dump(res, f, indent=2, sort_keys=True)
        f.write('\n')
    inv = res.get('invariants', {})
    for name in sorted(inv):
        print('%-34s %s' % (name, 'ok' if inv[name] else 'FAIL'))
    print('fleet: %s' % json.dumps(res.get('fleet', {}),
                                   sort_keys=True))
    ok = bool(inv) and all(inv.values())
    print('fleet_gate: %s -> %s' % ('PASS' if ok else 'FAIL',
                                    args.out))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
