#!/usr/bin/env python3
"""FDMT FRB-search flagship gate: the capture -> channelize -> FDMT ->
matched-filter -> threshold -> candidate-sink chain must be EXACT,
halo-carried, and inside its latency SLO — this publishes the
BENCH_FDMT_*.json artifact series.

Runs bench_suite config 22 (bench_suite.bench_fdmt_chain: three arms —
unfused block chain, halo-carried segment, halo-carried segment at
macro K=4 — interleaved over the same dispersed-pulse stream) in a
fresh subprocess pinned to the CPU backend, and asserts:

- ``byte_identical``          — all three arms' candidate streams are
  byte-identical: the in-program halo carry is a scheduling
  optimization, never a numerics change;
- ``oracle_within_rtol``      — every arm matches the sequential
  float64 numpy oracle (fdmt_numpy + fixed-order boxcar) within the
  FDMT race gate rtol (BF_FDMT_GATE_RTOL, default 1e-4);
- ``candidates_match_oracle`` — the candidate count at the fixed
  false-alarm rate matches the oracle's count (the headline
  candidates/s metric counts real detections, not numeric noise);
- ``halo_carry_engaged``      — under BF_SEGMENTS=force the chain
  compiled into ONE segment, the member blocks dispatched ZERO times,
  the ``segment.overlap_carried`` counter shows the ``overlap``
  boundary was lifted (BF-I192), and the interior rings registered
  zero span traffic under BF_RINGCHECK=1;
- ``p99_under_budget``        — capture-to-candidate exit age p99
  (worst arm) is under BF_SLO_MS.

Exit codes: 0 pass, 3 a gate condition failed, 2 the bench failed to
produce a result.  ``tools/watch_and_bench.sh`` runs this after the
FX-correlator gate (``BF_SKIP_FDMT_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_config22(timeout=1800):
    """One bench_suite --config 22 subprocess on the CPU backend;
    returns its result dict."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    # a configured global batch/donate/impl would skew the arm
    # comparison — the bench sets its own per-arm knobs
    env.pop('BF_GULP_BATCH', None)
    env.pop('BF_DONATE', None)
    env.pop('BF_FDMT_IMPL', None)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
         '--config', '22'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and 'fdmt' in d:
            return d
        if isinstance(d, dict) and d.get('error'):
            raise RuntimeError('config 22 failed: %s' % d['error'])
    raise RuntimeError(
        'config 22 produced no result (rc=%d):\n%s\n%s'
        % (out.returncode, out.stdout[-1000:], out.stderr[-1000:]))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    round_ = os.environ.get('BF_BENCH_ROUND', 'cpu')
    ap.add_argument('--out', default='BENCH_FDMT_%s.json' % round_,
                    help='artifact path (full config-22 result + '
                         'verdict)')
    ap.add_argument('--timeout', type=float, default=1800.0,
                    help='bench subprocess timeout in seconds')
    args = ap.parse_args()

    try:
        res = run_config22(timeout=args.timeout)
    except (RuntimeError, subprocess.TimeoutExpired) as exc:
        print('fdmt_gate: bench failed: %s' % exc, file=sys.stderr)
        return 2

    byte_ok = bool(res.get('byte_identical'))
    oracle_ok = bool(res.get('oracle_within_rtol'))
    cand_ok = bool(res.get('candidates_match_oracle'))
    carry_ok = bool(res.get('halo_carry_engaged'))
    slo = res.get('slo', {})
    slo_ok = bool(slo.get('p99_under_budget'))
    ok = byte_ok and oracle_ok and cand_ok and carry_ok and slo_ok
    artifact = dict(res,
                    gate={'byte_identical': byte_ok,
                          'oracle_within_rtol': oracle_ok,
                          'candidates_match_oracle': cand_ok,
                          'halo_carry_engaged': carry_ok,
                          'p99_under_budget': slo_ok,
                          'pass': ok,
                          'round': os.environ.get('BF_BENCH_ROUND',
                                                  '')})
    with open(args.out, 'w') as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write('\n')
    fd = res.get('fdmt', {})
    print('fdmt_gate: %s candidates/s (winner %s), %d candidates '
          '(oracle %d) @ FAR %s, p99 %.0f ms / budget %.0f ms, '
          'byte_identical=%s oracle_within_rtol=%s '
          'halo_carry_engaged=%s %s'
          % (fd.get('candidates_per_s', -1), fd.get('winner'),
             fd.get('candidates', -1), fd.get('oracle_candidates', -1),
             fd.get('false_alarm_rate'),
             slo.get('exit_age_p99_ms_worst_arm', -1),
             slo.get('budget_ms', -1), byte_ok, oracle_ok, carry_ok,
             'PASS' if ok else 'FAIL'))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
