#!/usr/bin/env python3
"""Merge per-host Chrome traces into ONE cross-host timeline.

Each bifrost_tpu process exports its spans on its OWN clock
(``time.perf_counter`` since process start — see telemetry/spans.py),
so two hosts' trace files cannot be overlaid directly.  The bridge
handshake solves this: every HELLO/HELLO_ACK exchange doubles as a
clock PING (io/bridge.py), and the sender embeds the estimated
peer-clock offset (accurate to ~RTT/2) into its trace export under
``otherData.bf_clock.sessions``.  This tool walks those session links
to put every input trace onto the FIRST input's clock and writes one
merged Chrome trace JSON:

    python tools/trace_merge.py -o merged.json host_a.json host_b.json

- Files are joined by bridge SESSION id: a file whose sessions entry
  carries an ``offset_us`` (the tx side) anchors its rx peer (the file
  registering the same session without an offset).  Chains work too
  (A->B->C shifts C by both hops' offsets).
- Unlinked files merge with zero shift and a warning (their relative
  position is then meaningless — but their spans are preserved).
- pids are renumbered per input file, with ``process_name`` metadata
  ``host=... pid=... (file)`` so Perfetto shows which host each track
  came from.

A gulp is then followable ACROSS hosts: compute spans carry
``args.trace`` (the stream-unique trace id from the header trace
context) plus ``(seq, gulp)``, and the bridge's ``bridge.tx.* /
bridge.rx.*`` spans carry the same triple, so selecting a trace id in
the merged view shows capture, transport, and remote commit on one
timeline.

Fleet incident bundles (telemetry.fleet's black-box recorder) are
accepted DIRECTLY: pass the bundle directory instead of trace files
and every ``hosts/<host>/flight.json`` timeline is merged, each host
shifted by its clock origin from the bundle's ``meta.json``
(``span_origin_wall_ns`` — the wall-clock instant of that host's
span-clock zero, stamped by the collector from the publisher's paired
wall/mono clocks):

    python tools/trace_merge.py -o merged.json \\
        incidents/incident_001_alert-host-absent
"""

import argparse
import json
import os
import sys


def load(path):
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or 'traceEvents' not in data:
        raise ValueError('%s is not a Chrome trace JSON' % path)
    return data


def is_bundle(path):
    """True when ``path`` is a fleet incident-bundle directory."""
    return (os.path.isdir(path)
            and os.path.isfile(os.path.join(path, 'meta.json')))


def expand_bundle(path):
    """(flight_paths, {path: origin_wall_ns}) for an incident bundle.

    The collector stamps each host's ``span_origin_wall_ns`` — the
    wall-clock time of that host's span-clock zero, derived from the
    publisher's paired wall/monotonic clocks — into the bundle's
    ``meta.json``.  That gives every flight.json an absolute anchor,
    so hosts align WITHOUT sharing a bridge session."""
    with open(os.path.join(path, 'meta.json')) as f:
        meta = json.load(f)
    host_meta = meta.get('hosts') or {}
    paths, origins = [], {}
    hosts_dir = os.path.join(path, 'hosts')
    names = sorted(os.listdir(hosts_dir)) if os.path.isdir(hosts_dir) \
        else []
    for host in names:
        flight = os.path.join(hosts_dir, host, 'flight.json')
        if not os.path.isfile(flight):
            continue
        paths.append(flight)
        origin = (host_meta.get(host) or {}).get('span_origin_wall_ns')
        if origin is not None:
            origins[flight] = float(origin)
    if not paths:
        raise ValueError('%s: incident bundle has no hosts/*/'
                         'flight.json timelines' % path)
    return paths, origins


def expand_inputs(inputs):
    """Expand bundle directories among ``inputs`` into their per-host
    flight traces; plain trace files pass through unchanged."""
    paths, origins = [], {}
    for item in inputs:
        if is_bundle(item):
            bpaths, borigins = expand_bundle(item)
            paths.extend(bpaths)
            origins.update(borigins)
        else:
            paths.append(item)
    return paths, origins


def trace_origin_ns(data):
    """A standalone trace's own wall-clock span origin, if stamped
    (flight.json exports carry it under otherData)."""
    origin = (data.get('otherData')
              or {}).get('bf_span_origin_wall_ns')
    return float(origin) if origin is not None else None


def clock_sessions(data):
    """{session: entry} from a trace file's bf_clock metadata."""
    other = data.get('otherData') or {}
    clock = other.get('bf_clock') or {}
    sessions = clock.get('sessions') or {}
    return {str(k): dict(v) for k, v in sessions.items()
            if isinstance(v, dict)}


def resolve_shifts(traces):
    """Per-file shift (us to ADD to its timestamps) onto file 0's
    clock, via BFS over shared bridge sessions.

    The tx side measured ``offset_us = rx_clock - tx_clock``; a
    timestamp from the rx file converts to the tx clock as
    ``t - offset_us``."""
    links = []                       # (tx_idx, rx_idx, offset_us)
    by_session = {}
    for idx, data in enumerate(traces):
        for session, entry in clock_sessions(data).items():
            by_session.setdefault(session, []).append((idx, entry))
    for session, members in by_session.items():
        txs = [(i, e) for i, e in members
               if e.get('offset_us') is not None]
        rxs = [(i, e) for i, e in members
               if e.get('offset_us') is None]
        for ti, te in txs:
            for ri, _re in rxs:
                if ti != ri:
                    links.append((ti, ri, float(te['offset_us'])))
    shifts = {0: 0.0}
    frontier = [0]
    while frontier:
        cur = frontier.pop()
        for ti, ri, off in links:
            if ti == cur and ri not in shifts:
                # rx file's clock -> tx file's clock: t - off, then
                # onto file 0's clock with the tx file's own shift
                shifts[ri] = shifts[ti] - off
                frontier.append(ri)
            elif ri == cur and ti not in shifts:
                shifts[ti] = shifts[ri] + off
                frontier.append(ti)
    return shifts


def merge(paths, origins=None):
    traces = [load(p) for p in paths]
    shifts = resolve_shifts(traces)
    # wall-clock anchoring (incident bundles): a file the session BFS
    # could not reach still aligns when both it and the reference
    # carry a span_origin_wall_ns stamp — a wall instant W sits at
    # (W - origin)/1e3 us on each file's clock, so
    # ts_ref = ts_file + (origin_file - origin_ref) / 1e3.
    origins = dict(origins or {})
    for idx, (path, data) in enumerate(zip(paths, traces)):
        if path not in origins:
            stamped = trace_origin_ns(data)
            if stamped is not None:
                origins[path] = stamped
    ref_origin = origins.get(paths[0]) if paths else None
    wall_shifted = set()
    events = []
    clocks = {}
    for idx, (path, data) in enumerate(zip(paths, traces)):
        shift = shifts.get(idx)
        if shift is None and ref_origin is not None \
                and path in origins:
            shift = (origins[path] - ref_origin) / 1e3
            wall_shifted.add(path)
        if shift is None:
            print('trace_merge: WARNING: %s shares no bridge session '
                  'with the reference trace and carries no wall-clock '
                  'origin — merged with zero shift (relative timing '
                  'meaningless)' % path, file=sys.stderr)
            shift = 0.0
        other = (data.get('otherData') or {}).get('bf_clock') or {}
        host = other.get('host',
                         (data.get('otherData') or {}).get('bf_host',
                                                           '?'))
        pid = idx + 1                # renumber: same-pid files collide
        clocks[path] = {'shift_us': round(shift, 3), 'host': host,
                        'orig_pid': other.get('pid')}
        if path in wall_shifted:
            clocks[path]['aligned_by'] = 'wall_origin'
        if path in origins:
            clocks[path]['span_origin_wall_ns'] = origins[path]
        # wall-clock skew to each bridge peer (the fabric end-to-end
        # SLO's correction term — docs/fabric.md): surfaced so an
        # operator can see host clock drift directly from the traces
        walls = {s: e['wall_offset_ns']
                 for s, e in clock_sessions(data).items()
                 if e.get('wall_offset_ns') is not None}
        if walls:
            clocks[path]['wall_offsets_ns'] = walls
        events.append({'ph': 'M', 'name': 'process_name', 'pid': pid,
                       'tid': 0,
                       'args': {'name': 'host=%s pid=%s (%s)'
                                % (host, other.get('pid', '?'),
                                   path)}})
        for ev in data['traceEvents']:
            ev = dict(ev)
            ev['pid'] = pid
            if 'ts' in ev and ev.get('ph') != 'M':
                ev['ts'] = round(ev['ts'] + shift, 3)
            events.append(ev)
    return {'traceEvents': events, 'displayTimeUnit': 'ms',
            'otherData': {'bf_merged_from': clocks}}


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('inputs', nargs='+',
                    help='per-host Chrome trace JSONs (BF_TRACE_FILE '
                         'exports) and/or fleet incident-bundle '
                         'directories; the first is the clock '
                         'reference')
    ap.add_argument('-o', '--out', required=True,
                    help='merged Chrome trace output path')
    args = ap.parse_args()
    paths, origins = expand_inputs(args.inputs)
    merged = merge(paths, origins)
    with open(args.out, 'w') as f:
        json.dump(merged, f)
    n = sum(1 for e in merged['traceEvents'] if e.get('ph') != 'M')
    print('trace_merge: %d event(s) from %d file(s) -> %s'
          % (n, len(paths), args.out))
    for path, info in merged['otherData']['bf_merged_from'].items():
        if info.get('aligned_by') == 'wall_origin':
            print('trace_merge: %s: clock offset from bundle '
                  'metadata: %+0.3f ms'
                  % (info.get('host', path),
                     info['shift_us'] / 1e3))
        for session, off in (info.get('wall_offsets_ns')
                             or {}).items():
            print('trace_merge: %s: wall-clock offset to bridge peer '
                  '(session %s): %+0.3f ms'
                  % (info.get('host', path), session[:8], off / 1e6))
    return 0


if __name__ == '__main__':
    sys.exit(main())
