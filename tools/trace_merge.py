#!/usr/bin/env python3
"""Merge per-host Chrome traces into ONE cross-host timeline.

Each bifrost_tpu process exports its spans on its OWN clock
(``time.perf_counter`` since process start — see telemetry/spans.py),
so two hosts' trace files cannot be overlaid directly.  The bridge
handshake solves this: every HELLO/HELLO_ACK exchange doubles as a
clock PING (io/bridge.py), and the sender embeds the estimated
peer-clock offset (accurate to ~RTT/2) into its trace export under
``otherData.bf_clock.sessions``.  This tool walks those session links
to put every input trace onto the FIRST input's clock and writes one
merged Chrome trace JSON:

    python tools/trace_merge.py -o merged.json host_a.json host_b.json

- Files are joined by bridge SESSION id: a file whose sessions entry
  carries an ``offset_us`` (the tx side) anchors its rx peer (the file
  registering the same session without an offset).  Chains work too
  (A->B->C shifts C by both hops' offsets).
- Unlinked files merge with zero shift and a warning (their relative
  position is then meaningless — but their spans are preserved).
- pids are renumbered per input file, with ``process_name`` metadata
  ``host=... pid=... (file)`` so Perfetto shows which host each track
  came from.

A gulp is then followable ACROSS hosts: compute spans carry
``args.trace`` (the stream-unique trace id from the header trace
context) plus ``(seq, gulp)``, and the bridge's ``bridge.tx.* /
bridge.rx.*`` spans carry the same triple, so selecting a trace id in
the merged view shows capture, transport, and remote commit on one
timeline.
"""

import argparse
import json
import sys


def load(path):
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or 'traceEvents' not in data:
        raise ValueError('%s is not a Chrome trace JSON' % path)
    return data


def clock_sessions(data):
    """{session: entry} from a trace file's bf_clock metadata."""
    other = data.get('otherData') or {}
    clock = other.get('bf_clock') or {}
    sessions = clock.get('sessions') or {}
    return {str(k): dict(v) for k, v in sessions.items()
            if isinstance(v, dict)}


def resolve_shifts(traces):
    """Per-file shift (us to ADD to its timestamps) onto file 0's
    clock, via BFS over shared bridge sessions.

    The tx side measured ``offset_us = rx_clock - tx_clock``; a
    timestamp from the rx file converts to the tx clock as
    ``t - offset_us``."""
    links = []                       # (tx_idx, rx_idx, offset_us)
    by_session = {}
    for idx, data in enumerate(traces):
        for session, entry in clock_sessions(data).items():
            by_session.setdefault(session, []).append((idx, entry))
    for session, members in by_session.items():
        txs = [(i, e) for i, e in members
               if e.get('offset_us') is not None]
        rxs = [(i, e) for i, e in members
               if e.get('offset_us') is None]
        for ti, te in txs:
            for ri, _re in rxs:
                if ti != ri:
                    links.append((ti, ri, float(te['offset_us'])))
    shifts = {0: 0.0}
    frontier = [0]
    while frontier:
        cur = frontier.pop()
        for ti, ri, off in links:
            if ti == cur and ri not in shifts:
                # rx file's clock -> tx file's clock: t - off, then
                # onto file 0's clock with the tx file's own shift
                shifts[ri] = shifts[ti] - off
                frontier.append(ri)
            elif ri == cur and ti not in shifts:
                shifts[ti] = shifts[ri] + off
                frontier.append(ti)
    return shifts


def merge(paths):
    traces = [load(p) for p in paths]
    shifts = resolve_shifts(traces)
    events = []
    clocks = {}
    for idx, (path, data) in enumerate(zip(paths, traces)):
        shift = shifts.get(idx)
        if shift is None:
            print('trace_merge: WARNING: %s shares no bridge session '
                  'with the reference trace — merged with zero shift '
                  '(relative timing meaningless)' % path,
                  file=sys.stderr)
            shift = 0.0
        other = (data.get('otherData') or {}).get('bf_clock') or {}
        host = other.get('host', '?')
        pid = idx + 1                # renumber: same-pid files collide
        clocks[path] = {'shift_us': round(shift, 3), 'host': host,
                        'orig_pid': other.get('pid')}
        # wall-clock skew to each bridge peer (the fabric end-to-end
        # SLO's correction term — docs/fabric.md): surfaced so an
        # operator can see host clock drift directly from the traces
        walls = {s: e['wall_offset_ns']
                 for s, e in clock_sessions(data).items()
                 if e.get('wall_offset_ns') is not None}
        if walls:
            clocks[path]['wall_offsets_ns'] = walls
        events.append({'ph': 'M', 'name': 'process_name', 'pid': pid,
                       'tid': 0,
                       'args': {'name': 'host=%s pid=%s (%s)'
                                % (host, other.get('pid', '?'),
                                   path)}})
        for ev in data['traceEvents']:
            ev = dict(ev)
            ev['pid'] = pid
            if 'ts' in ev and ev.get('ph') != 'M':
                ev['ts'] = round(ev['ts'] + shift, 3)
            events.append(ev)
    return {'traceEvents': events, 'displayTimeUnit': 'ms',
            'otherData': {'bf_merged_from': clocks}}


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('inputs', nargs='+',
                    help='per-host Chrome trace JSONs (BF_TRACE_FILE '
                         'exports); the first is the clock reference')
    ap.add_argument('-o', '--out', required=True,
                    help='merged Chrome trace output path')
    args = ap.parse_args()
    merged = merge(args.inputs)
    with open(args.out, 'w') as f:
        json.dump(merged, f)
    n = sum(1 for e in merged['traceEvents'] if e.get('ph') != 'M')
    print('trace_merge: %d event(s) from %d file(s) -> %s'
          % (n, len(args.inputs), args.out))
    for path, info in merged['otherData']['bf_merged_from'].items():
        for session, off in (info.get('wall_offsets_ns')
                             or {}).items():
            print('trace_merge: %s: wall-clock offset to bridge peer '
                  '(session %s): %+0.3f ms'
                  % (info.get('host', path), session[:8], off / 1e6))
    return 0


if __name__ == '__main__':
    sys.exit(main())
