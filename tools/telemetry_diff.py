#!/usr/bin/env python3
"""Regression sentinel: diff two telemetry snapshots or bench
artifacts and flag drifts beyond thresholds.

    python tools/telemetry_diff.py BASELINE.json CURRENT.json
    python tools/telemetry_diff.py old_snap.json new_snap.json --strict

Accepts any JSON the framework emits — ``telemetry.snapshot()`` dumps,
``BENCH_*.json`` bench artifacts, gate artifacts — and compares every
numeric leaf it can match between the two files (flattened to
dot-paths).  A built-in watchlist knows which metrics matter and which
DIRECTION is bad:

    pattern                 worse when   threshold
    gulps_per_s / GBps /
      Msamples/s /
      value (throughput unit)  lower      10%%
    *_p99 / p99* / *_ms /
      ms_per_gulp / wait /
      value (latency unit)    higher     25%%
    occupancy_pct             higher     20 points (absolute)
    violations / dropped /
      crc_errors / reconnects
      / fallback              higher     any increase
    segment.elided_rings /
      segment.dispatches      lower      any decrease (fusion
                                         silently disengaged)
    overhead_pct              higher     2 points (absolute)
    fleet.*_errors /
      fleet.pub.errors /
      alerts.sink_errors /
      incident.errors         higher     any increase (telemetry
                                         silently dropping)
    fleet.hosts_live          lower      any decrease (a publisher
                                         stopped streaming)
    fdmt.candidates_per_s     lower      10%%
    segment.overlap_carried   lower      any decrease (halo carry
                                         silently disengaged)
    capture.pps /
      capture.gbps            lower      10%% (zero-copy batched
                                         capture path disengaged)
    capture.loss_frac         higher     +0.005 absolute

Unmatched numeric keys are compared informationally (reported at
>50%% drift, never flagged).  Exit code 0 = no regressions (advisory
mode, the default, ALWAYS exits 0 unless the inputs are unreadable);
``--strict`` exits 3 when any watched metric regressed beyond its
threshold — ``tools/watch_and_bench.sh`` runs the advisory mode
against the previous round's artifact after each capture.  ``--out``
writes the full report as JSON.
"""

import argparse
import fnmatch
import json
import sys

#: (glob over the flattened dot-path, direction, kind, threshold)
#: direction: 'lower' = lower is worse, 'higher' = higher is worse
#: kind: 'pct' relative %, 'abs' absolute delta, 'any' any worsening
WATCHLIST = [
    ('*gulps_per_s*', 'lower', 'pct', 10.0),
    ('*GBps*', 'lower', 'pct', 10.0),
    ('*Msamples*', 'lower', 'pct', 10.0),
    # bench 'value' keys are direction-tagged by flatten() from the
    # sibling 'unit' string: most configs report a speedup/throughput
    # (higher better), but e.g. BENCH_E2E's value is a latency p99
    ('*value_throughput', 'lower', 'pct', 10.0),
    ('*value_latency', 'higher', 'pct', 25.0),
    ('*overhead_pct*', 'higher', 'abs', 2.0),
    ('*occupancy_pct*', 'higher', 'abs', 20.0),
    ('*p99*', 'higher', 'pct', 25.0),
    ('*_ms*', 'higher', 'pct', 25.0),
    ('*ms_per_gulp*', 'higher', 'pct', 25.0),
    ('*wait*', 'higher', 'pct', 25.0),
    ('*violations*', 'higher', 'any', 0.0),
    ('*dropped*', 'higher', 'any', 0.0),
    # compiled pipeline segments (docs/perf.md): fewer elided rings or
    # less dispatch traffic through segments between same-config
    # rounds means fusion silently disengaged — a perf regression even
    # when wall-clock noise hides it
    ('*segment.elided_rings*', 'lower', 'any', 0.0),
    ('*segment.dispatches*', 'lower', 'any', 0.0),
    # FX correlator flagship (BENCH_FXCORR, config 19): the raced
    # X-engine's winner rate — a drop means the quantized candidate
    # stopped winning or the race landed somewhere slower
    ('*xengine.gops_per_s*', 'lower', 'pct', 10.0),
    # FDMT FRB-search flagship (BENCH_FDMT, config 22): the headline
    # candidates/s at fixed false-alarm rate, and the halo-carry
    # engagement counter — overlap_carried dropping between
    # same-config rounds means the in-program halo carry silently
    # disengaged and the chain fell back to per-gulp overlapped reads
    ('*fdmt.candidates_per_s*', 'lower', 'pct', 10.0),
    ('*segment.overlap_carried*', 'lower', 'any', 0.0),
    # elastic control plane (SCHED_CHAOS, config 20): the chaos drill
    # SIGKILLs a host mid-stream — fewer migrations or re-placement
    # events between same-config rounds means the death watch or the
    # re-placement path silently disengaged and the drill stopped
    # exercising what it gates
    # (no trailing glob: 'replacements_refused' DROPPING is fine)
    ('*scheduler.migrations', 'lower', 'any', 0.0),
    ('*scheduler.replacements', 'lower', 'any', 0.0),
    # wire-rate capture flagship (BENCH_CAPTURE, config 23): sustained
    # ingest rate of the sharded zero-copy engine — a pps/gbps drop
    # between same-config rounds usually means the zero-copy batched
    # path silently disengaged (every packet still arrives, each just
    # pays the staging copy again); loss_frac is gated absolutely
    ('*capture.pps*', 'lower', 'pct', 10.0),
    ('*capture.gbps*', 'lower', 'pct', 10.0),
    ('*capture.loss_frac*', 'higher', 'abs', 0.005),
    ('*crc_errors*', 'higher', 'any', 0.0),
    ('*reconnects*', 'higher', 'any', 0.0),
    ('*fallback*', 'higher', 'any', 0.0),
    # fleet observability plane (FLEET_OBS, config 21): decode or
    # tick errors on the collector, publish-side send errors, or
    # alert-sink write failures mean telemetry is silently dropping
    # on the floor between rounds; rollup files nest these per host
    # (hosts.<h>.counters.*) and flatten() already yields those paths
    ('*fleet.decode_errors*', 'higher', 'any', 0.0),
    ('*fleet.tick_errors*', 'higher', 'any', 0.0),
    ('*fleet.pub.errors*', 'higher', 'any', 0.0),
    ('*alerts.sink_errors*', 'higher', 'any', 0.0),
    ('*incident.errors*', 'higher', 'any', 0.0),
    # fewer live hosts for the same config means a publisher stopped
    # streaming (or the collector stopped adopting) — the fleet-plane
    # analogue of scheduler.replacements disengaging
    ('*fleet.hosts_live', 'lower', 'any', 0.0),
]

#: flattened paths never worth comparing (identities, timestamps,
#: environment echoes)
IGNORE = ['*round*', '*.buckets.*', '*origin_ns*',
          '*.min', '*.max', '*.sum', '*time_tag*', '*.pid',
          '*threshold*']


#: unit substrings marking a bench 'value' as a latency (higher worse)
_LATENCY_UNITS = ('ms', 'latency', 'age', 'seconds')


def flatten(obj, prefix=''):
    """{dot.path: float} over every numeric leaf (bools excluded).

    A dict's 'value' key is direction-AMBIGUOUS across bench configs
    (most report a speedup — higher better — but e.g. BENCH_E2E's is a
    latency p99), so when a sibling 'unit' string is present the key
    is rewritten to ``value_latency`` / ``value_throughput`` for the
    watchlist to match; a unit-less 'value' stays unmatched
    (informational only)."""
    out = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            if k == 'value' and isinstance(obj.get('unit'), str):
                unit = obj['unit'].lower()
                k = 'value_latency' if any(u in unit for u
                                           in _LATENCY_UNITS) \
                    else 'value_throughput'
            out.update(flatten(v, '%s.%s' % (prefix, k) if prefix
                               else str(k)))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix] = float(obj)
    return out


def watch_rule(path):
    for pat, direction, kind, thresh in WATCHLIST:
        if fnmatch.fnmatch(path, pat):
            return direction, kind, thresh
    return None


def compare(base, cur):
    """Findings over the keys present in BOTH files."""
    fb, fc = flatten(base), flatten(cur)
    findings = []
    for path in sorted(set(fb) & set(fc)):
        if any(fnmatch.fnmatch(path, pat) for pat in IGNORE):
            continue
        b, c = fb[path], fc[path]
        rule = watch_rule(path)
        delta = c - b
        # None, not inf: % change from a 0 base is undefined, and
        # Infinity is not valid JSON in the --out report
        pct = (delta / abs(b) * 100.0) if b else \
            (0.0 if not delta else None)
        if rule is None:
            # informational: large unmatched drifts are still worth a
            # line in the report, but never a regression verdict
            if b and abs(pct) > 50.0:
                findings.append({'path': path, 'base': b, 'cur': c,
                                 'pct': round(pct, 1),
                                 'severity': 'info'})
            continue
        direction, kind, thresh = rule
        worse = delta > 0 if direction == 'higher' else delta < 0
        if not worse:
            continue
        if kind == 'any':
            trip = abs(delta) > 0
        elif kind == 'abs':
            trip = abs(delta) > thresh
        else:
            # pct rule against a 0 base: the relative change is
            # unbounded, so any worsening trips
            trip = True if pct is None else abs(pct) > thresh
        findings.append({'path': path, 'base': b, 'cur': c,
                         'pct': None if pct is None else round(pct, 1),
                         'delta': round(delta, 6),
                         'direction': direction, 'kind': kind,
                         'threshold': thresh,
                         'severity': 'regression' if trip else 'drift'})
    return findings


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('baseline', help='previous snapshot/artifact JSON')
    ap.add_argument('current', help='new snapshot/artifact JSON')
    ap.add_argument('--out', default=None,
                    help='write the full report as JSON here')
    ap.add_argument('--strict', action='store_true',
                    help='exit 3 when any watched metric regressed '
                         'beyond threshold (default: advisory, '
                         'always exit 0)')
    args = ap.parse_args()
    try:
        with open(args.baseline) as f:
            base = json.load(f)
        with open(args.current) as f:
            cur = json.load(f)
    except (OSError, ValueError) as exc:
        print('telemetry_diff: cannot read inputs: %s' % exc,
              file=sys.stderr)
        return 2

    findings = compare(base, cur)
    regressions = [f for f in findings
                   if f['severity'] == 'regression']
    for f in findings:
        mark = {'regression': 'REGRESSED', 'drift': 'drift',
                'info': 'info'}[f['severity']]
        pct_s = ('%+.1f%%' % f['pct']) if f['pct'] is not None \
            else 'n/a'
        print('%-10s %-50s %g -> %g (%s)'
              % (mark, f['path'], f['base'], f['cur'], pct_s))
    verdict = 'REGRESSED' if regressions else 'OK'
    print('telemetry_diff: %s — %d finding(s), %d regression(s) '
          '(%s vs %s)' % (verdict, len(findings), len(regressions),
                          args.current, args.baseline))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump({'baseline': args.baseline,
                       'current': args.current,
                       'findings': findings,
                       'regressions': len(regressions),
                       'pass': not regressions}, f, indent=1,
                      sort_keys=True)
            f.write('\n')
    if args.strict and regressions:
        return 3
    return 0


if __name__ == '__main__':
    sys.exit(main())
