#!/usr/bin/env python3
"""Fleet console: stand up a FleetCollector and watch the rollup.

The console is the receiving end of the streaming telemetry plane
(docs/observability.md "Fleet plane"): it binds the collector's UDP
port, prints the address publishers should stream to (point each
host's ``BF_FLEET_COLLECTOR`` at it), and renders the merged per-host
/ per-tenant / alert view on an interval — the same renderer as
``like_top.py --fleet``.

    # collector + live text view, alert rules + black-box recorder on
    python tools/bf_console.py --bind 0.0.0.0:9720 \\
        --rules alert_rules.json --incident-dir ./incidents \\
        --prom-file /var/lib/node_exporter/bifrost_fleet.prom

    # with fabric death verdicts (unknown-vs-dead — docs/fabric.md)
    python tools/bf_console.py --fabric fabric.json --host head

``--once`` waits one interval and prints a single snapshot (usable in
pipes/tests); ``--duration`` bounds the run for scripted drills.
Exports keep flowing while the console renders: ``--rollup-file``
feeds other ``like_top --fleet`` instances, ``--prom-file`` a node
exporter.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bifrost_tpu.telemetry import fleet  # noqa: E402
from like_top import render_fleet  # noqa: E402


def _parse_bind(value):
    host, _, port = value.rpartition(':')
    if not host:
        host, port = value, '0'
    return host, int(port)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--bind', default='127.0.0.1:0',
                    help='UDP address to receive telemetry on '
                         '(host:port; port 0 picks one)')
    ap.add_argument('--rules', default=None,
                    help='alert-rules JSON (default: BF_ALERT_RULES)')
    ap.add_argument('--incident-dir', default=None,
                    help='black-box bundle directory (default: '
                         'BF_FLEET_INCIDENT_DIR)')
    ap.add_argument('--rollup-file', default=None,
                    help='also write the rollup JSON here each tick '
                         '(default: BF_FLEET_ROLLUP_FILE)')
    ap.add_argument('--prom-file', default=None,
                    help='also write the merged Prometheus textfile '
                         '(default: BF_FLEET_PROM_FILE)')
    ap.add_argument('--fabric', default=None,
                    help='FabricSpec JSON: run Membership for death '
                         'verdicts (needs --host)')
    ap.add_argument('--host', default=None,
                    help='this host\'s name in the fabric spec')
    ap.add_argument('--interval', type=float, default=2.0,
                    help='render interval in seconds')
    ap.add_argument('--duration', type=float, default=None,
                    help='exit after this many seconds')
    ap.add_argument('--once', action='store_true',
                    help='wait one interval, print one snapshot, exit')
    args = ap.parse_args()

    membership = None
    if args.fabric:
        if not args.host:
            print('bf_console: --fabric needs --host', file=sys.stderr)
            return 2
        from bifrost_tpu.fabric import FabricSpec, Membership
        spec = FabricSpec.load(args.fabric)
        membership = Membership(spec, args.host)
        membership.start()

    rules = fleet.load_rules(args.rules) if args.rules \
        else fleet.load_rules()
    coll = fleet.FleetCollector(
        bind=_parse_bind(args.bind), membership=membership,
        rules=rules, incident_dir=args.incident_dir,
        rollup_file=args.rollup_file, prom_file=args.prom_file)
    coll.start()
    print('bf_console: collecting on %s:%d — set '
          'BF_FLEET_COLLECTOR=<this-host>:%d on each publisher'
          % (coll.bind_host, coll.port, coll.port))
    t0 = time.monotonic()
    try:
        while True:
            time.sleep(args.interval)
            lines = render_fleet(coll.rollup())
            print('\n'.join(lines))
            print('')
            sys.stdout.flush()
            if args.once:
                break
            if args.duration is not None and \
                    time.monotonic() - t0 >= args.duration:
                break
    except KeyboardInterrupt:
        pass
    finally:
        coll.stop()
        if membership is not None:
            membership.stop()
    return 0


if __name__ == '__main__':
    sys.exit(main())
