#!/usr/bin/env python3
"""Verify gate: run the static pipeline verifier over every
pipeline-shaped bench_suite config and every examples/ pipeline
(docs/analysis.md), via tools/bf_lint.py.

    python tools/verify_gate.py [--out VERIFY_GATE.json] [--strict]

For each registered bench topology (``bench_suite.
build_verify_topologies``: the config 8/9/10/11/12 chains) a
subprocess lints the build-only pipeline graph; each example script
runs under ``BF_LINT=1`` so its ``Pipeline.run()`` validates and
returns without executing.  The mesh topology gets an 8-device host
platform (``--xla_force_host_platform_device_count``), matching
tools/mesh_gate.py.

Verdict: PASS when every target lints with **zero BF-E errors**
(warnings are reported but advisory — the per-code strictness belongs
to bf_lint --strict on individual targets).  A target that cannot be
linted at all counts as a failure.

Exit codes match tools/telemetry_diff.py's convention: 0 = pass (or
advisory mode), 3 = ``--strict`` and errors / unlintable targets, 2 =
the gate itself could not run.  ``tools/watch_and_bench.sh`` runs the
strict mode after a successful bench capture; ``BF_SKIP_VERIFY_GATE=1``
opts out.
"""

import argparse
import glob
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BF_LINT = os.path.join(ROOT, 'tools', 'bf_lint.py')

#: per-example extra argv (scripts that print usage and exit without
#: arguments)
EXAMPLE_ARGS = {'gpuspec_simple.py': ['--demo']}

#: examples with no Pipeline to lint would be failures, none today —
#: keep the hook for future scripts that are pure libraries
EXAMPLE_SKIP = ()


def run_lint(argv, env=None, timeout=600):
    e = dict(os.environ)
    e.setdefault('JAX_PLATFORMS', 'cpu')
    if env:
        e.update(env)
    proc = subprocess.run([sys.executable, BF_LINT] + argv,
                          capture_output=True, text=True, env=e,
                          cwd=ROOT, timeout=timeout)
    return proc


def parse_summary(stdout):
    """(pipelines, errors, warnings) from bf_lint's summary line."""
    for line in stdout.splitlines():
        if line.startswith('bf_lint:') and 'error(s)' in line:
            words = line.split()
            try:
                ip = words.index('pipeline(s),')
                return (int(words[ip - 1]), int(words[ip + 1]),
                        int(words[ip + 3]))
            except (ValueError, IndexError):
                pass
    return None


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default='VERIFY_GATE.json',
                    help='verdict artifact path')
    ap.add_argument('--strict', action='store_true',
                    help='exit 3 on any BF-E / unlintable target '
                         '(default: advisory, exit 0)')
    ap.add_argument('--timeout', type=float, default=600.0)
    args = ap.parse_args()

    if os.environ.get('BF_SKIP_VERIFY_GATE', '0') == '1':
        print('verify_gate: skipped (BF_SKIP_VERIFY_GATE=1)')
        return 0

    targets = []
    # bench topologies (in a subprocess each: the mesh one needs its
    # own XLA host-platform device count, set before jax imports)
    sys.path.insert(0, ROOT)
    try:
        import bench_suite
        topo_names = sorted(bench_suite.build_verify_topologies())
    except Exception as exc:
        print('verify_gate: cannot enumerate bench topologies: %s'
              % exc, file=sys.stderr)
        return 2
    for name in topo_names:
        env = {}
        if 'mesh' in name:
            env['XLA_FLAGS'] = '--xla_force_host_platform_device_count=8'
        targets.append(('bench:%s' % name, ['--topology', name], env))
    for path in sorted(glob.glob(os.path.join(ROOT, 'examples',
                                              '*.py'))):
        base = os.path.basename(path)
        if base in EXAMPLE_SKIP:
            continue
        argv = [os.path.join('examples', base)] + \
            EXAMPLE_ARGS.get(base, [])
        targets.append(('example:%s' % base, argv, {}))

    results = []
    total_err = unlintable = 0
    for label, argv, env in targets:
        try:
            proc = run_lint(argv, env=env, timeout=args.timeout)
        except subprocess.TimeoutExpired:
            results.append({'target': label, 'ok': False,
                            'error': 'timeout'})
            unlintable += 1
            print('verify_gate: %-28s TIMEOUT' % label)
            continue
        summary = parse_summary(proc.stdout)
        if proc.returncode != 0 or summary is None:
            # rc 0 with no summary = an explicitly skipped topology
            if proc.returncode == 0 and 'skipped' in proc.stdout:
                results.append({'target': label, 'ok': True,
                                'skipped': True})
                print('verify_gate: %-28s skipped' % label)
                continue
            results.append({'target': label, 'ok': False,
                            'error': 'unlintable (rc=%d)'
                                     % proc.returncode,
                            'stderr': proc.stderr[-1000:]})
            unlintable += 1
            print('verify_gate: %-28s UNLINTABLE (rc=%d)'
                  % (label, proc.returncode))
            continue
        np_, ne, nw = summary
        total_err += ne
        results.append({'target': label, 'ok': ne == 0,
                        'pipelines': np_, 'errors': ne,
                        'warnings': nw})
        print('verify_gate: %-28s %d pipeline(s)  %d error(s)  '
              '%d warning(s)' % (label, np_, ne, nw))
        if ne or nw:
            for line in proc.stdout.splitlines():
                if line.startswith('BF-'):
                    print('    ' + line)

    ok = total_err == 0 and unlintable == 0
    artifact = {
        'targets': results,
        'total_errors': total_err,
        'unlintable': unlintable,
        'pass': ok,
        'round': os.environ.get('BF_BENCH_ROUND', ''),
    }
    with open(args.out, 'w') as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write('\n')
    print('verify_gate: %s — %d target(s), %d error(s), %d '
          'unlintable -> %s'
          % ('PASS' if ok else 'FAIL', len(targets), total_err,
             unlintable, args.out))
    if not ok and args.strict:
        return 3
    return 0


if __name__ == '__main__':
    sys.exit(main())
