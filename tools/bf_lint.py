#!/usr/bin/env python3
"""bf_lint: run the static pipeline verifier over a pipeline script or
a named bench topology WITHOUT running the pipeline (docs/analysis.md).

    python tools/bf_lint.py examples/fdmt_search.py
    python tools/bf_lint.py --topology config8_chain
    python tools/bf_lint.py --list-topologies
    python tools/bf_lint.py --codes

**Script mode**: the script runs in a subprocess with ``BF_LINT=1``,
which makes every ``Pipeline.run()`` validate the constructed
block/ring graph, report its diagnostics, and return WITHOUT launching
block threads — the script executes end to end as a pure topology
builder.  Post-run script logic that expects real output may fail;
that is tolerated as long as at least one pipeline was linted (the
diagnostics were already captured through ``BF_LINT_OUT``).

**Topology mode**: ``--topology NAME`` builds one of the registered
bench_suite pipeline topologies in-process (``bench_suite.
build_verify_topologies``) and validates it directly — this is how
``tools/verify_gate.py`` sweeps every pipeline-shaped bench config.

Exit codes (matching tools/telemetry_diff.py's convention): 0 =
advisory mode, or strict mode with no ``BF-E``; 3 = ``--strict`` and
at least one ``BF-E`` diagnostic; 2 = the target could not be linted
at all (script crashed before building a pipeline, unknown topology).
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def lint_script(path, args, timeout):
    """Run ``path`` under BF_LINT=1; returns (records, proc) where
    records is the list of per-pipeline diagnostic dicts collected via
    BF_LINT_OUT."""
    out = tempfile.NamedTemporaryFile(prefix='bf_lint_', suffix='.jsonl',
                                      delete=False)
    out.close()
    env = dict(os.environ)
    env['BF_LINT'] = '1'
    env['BF_LINT_OUT'] = out.name
    env.setdefault('JAX_PLATFORMS', 'cpu')
    proc = subprocess.run([sys.executable, path] + list(args),
                          capture_output=True, text=True, env=env,
                          cwd=ROOT, timeout=timeout)
    records = []
    try:
        with open(out.name) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        records.append(json.loads(line))
                    except ValueError:
                        pass
    finally:
        os.unlink(out.name)
    return records, proc


def lint_topology(name):
    """Build one registered bench topology in-process and validate it.
    Returns the per-pipeline record list (a topology may build several
    pipelines), or None when the topology reports itself unavailable
    on this host (e.g. a mesh topology without enough devices)."""
    import bench_suite
    builders = bench_suite.build_verify_topologies()
    if name not in builders:
        raise KeyError('unknown topology %r (have: %s)'
                       % (name, ', '.join(sorted(builders))))
    built = builders[name]()
    if built is None:
        return None
    pipelines = built if isinstance(built, (list, tuple)) else [built]
    records = []
    for p in pipelines:
        diags = p.validate()
        records.append({'pipeline': p.name, 'nblocks': len(p.blocks),
                        'diagnostics': [d.as_dict() for d in diags]})
    return records


def summarize(records, label, show_info=False):
    ne = nw = ni = 0
    for rec in records:
        for d in rec['diagnostics']:
            sev = d['severity']
            ne += sev == 'error'
            nw += sev == 'warning'
            ni += sev == 'info'
            if sev == 'info' and not show_info:
                continue
            where = d.get('block') or ''
            if d.get('ring'):
                where += ('@' if where else '') + 'ring:%s' % d['ring']
            print('%s %-9s %-40s %s' % (d['code'], sev, where,
                                        d['message']))
    print('bf_lint: %s — %d pipeline(s), %d error(s), %d warning(s), '
          '%d info' % (label, len(records), ne, nw, ni))
    return ne


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('script', nargs='?',
                    help='pipeline script to lint (BF_LINT=1 mode)')
    ap.add_argument('script_args', nargs=argparse.REMAINDER,
                    help='arguments passed through to the script')
    ap.add_argument('--topology', default=None,
                    help='lint a named bench_suite topology in-process')
    ap.add_argument('--list-topologies', action='store_true',
                    help='list registered bench topologies and exit')
    ap.add_argument('--codes', action='store_true',
                    help='print the diagnostic-code catalog and exit')
    ap.add_argument('--strict', action='store_true',
                    help='exit 3 when any BF-E diagnostic is reported '
                         '(default: advisory, exit 0)')
    ap.add_argument('--show-info', action='store_true',
                    help='print BF-I info diagnostics too')
    ap.add_argument('--timeout', type=float, default=300.0,
                    help='script-mode subprocess timeout (seconds)')
    args = ap.parse_args()

    if args.codes:
        from bifrost_tpu.analysis.verify import CODES
        for code in sorted(CODES):
            print('%s  %s' % (code, CODES[code]))
        return 0
    if args.list_topologies:
        import bench_suite
        for name in sorted(bench_suite.build_verify_topologies()):
            print(name)
        return 0

    if args.topology:
        try:
            records = lint_topology(args.topology)
        except KeyError as exc:
            print('bf_lint: %s' % exc, file=sys.stderr)
            return 2
        if records is None:
            print('bf_lint: topology %r unavailable on this host '
                  '(skipped)' % args.topology)
            return 0
        nerr = summarize(records, 'topology %s' % args.topology,
                         args.show_info)
        return 3 if (args.strict and nerr) else 0

    if not args.script:
        print('bf_lint: a script path or --topology is required '
              '(see --help)', file=sys.stderr)
        return 2
    try:
        records, proc = lint_script(args.script, args.script_args,
                                    args.timeout)
    except subprocess.TimeoutExpired:
        print('bf_lint: %s timed out' % args.script, file=sys.stderr)
        return 2
    if not records:
        print('bf_lint: %s built no pipeline under BF_LINT=1 '
              '(rc=%d)\n%s' % (args.script, proc.returncode,
                               proc.stderr[-2000:]), file=sys.stderr)
        return 2
    nerr = summarize(records, args.script, args.show_info)
    return 3 if (args.strict and nerr) else 0


if __name__ == '__main__':
    sys.exit(main())
