#!/usr/bin/env python3
"""Macro-gulp batch gate: K=16 must not be slower than K=1 on CPU.

Runs bench_suite config 9 (the config-8 gulp chain at K in {1,4,16}
macro-gulp batch — bench_suite.bench_gulp_batch) in a fresh subprocess
pinned to the CPU backend, and asserts:

- ``throughput_ok``  — the K=16 arm's min-of-N wall time is not worse
  than K=1's by more than ``--threshold`` percent (batched dispatch
  must never cost throughput where it cannot win it; on the real chip
  it is the ~6x headroom lever, see docs/perf.md);
- ``dispatch_ratio_ok`` — the fused block's dispatches/gulp at K=16 is
  at most 1/8 of the K=1 arm (the amortization actually engaged rather
  than silently falling back to K=1);
- ``outputs_identical`` — the batched arms produced byte-identical
  output streams to K=1.

The arm interleaving / min-of-N noise defenses live inside config 9
itself (same policy as the observability gate: per-arm minima,
alternating arm order between repetitions).  The full config result is
written to the ``--out`` JSON artifact so bench rounds record the
batch path's health next to the throughput numbers.

Exit codes: 0 pass, 3 a gate condition failed, 2 the bench arm failed
to produce a result.  ``tools/watch_and_bench.sh`` runs this after the
observability gate (``BF_SKIP_BATCH_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_config9(timeout=1800):
    """One bench_suite --config 9 subprocess on the CPU backend;
    returns its result dict."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    # a configured global batch would skew the K=1 arm
    env.pop('BF_GULP_BATCH', None)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
         '--config', '9'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and 'arms' in d:
            return d
    raise RuntimeError(
        'config 9 produced no arms result (rc=%d):\n%s\n%s'
        % (out.returncode, out.stdout[-1000:], out.stderr[-1000:]))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default='BENCH_BATCH.json',
                    help='artifact path (full config-9 result + verdict)')
    ap.add_argument('--threshold', type=float, default=5.0,
                    help='max allowed K=16 throughput regression vs '
                         'K=1, percent')
    ap.add_argument('--timeout', type=float, default=1800.0,
                    help='bench subprocess timeout in seconds')
    args = ap.parse_args()

    try:
        res = run_config9(timeout=args.timeout)
    except (RuntimeError, subprocess.TimeoutExpired) as exc:
        print('batch_gate: bench arm failed: %s' % exc,
              file=sys.stderr)
        return 2

    t1 = float(res['arms']['K1']['ms_min'])
    t16 = float(res['arms']['K16']['ms_min'])
    regression_pct = (t16 / t1 - 1.0) * 100.0 if t1 > 0 else 0.0
    throughput_ok = regression_pct < args.threshold
    dispatch_ok = bool(res.get('dispatch_ratio_ok'))
    outputs_ok = bool(res.get('outputs_identical'))
    ok = throughput_ok and dispatch_ok and outputs_ok
    artifact = dict(res,
                    gate={'regression_pct': round(regression_pct, 2),
                          'threshold_pct': args.threshold,
                          'throughput_ok': throughput_ok,
                          'dispatch_ratio_ok': dispatch_ok,
                          'outputs_identical': outputs_ok,
                          'pass': ok,
                          'round': os.environ.get('BF_BENCH_ROUND',
                                                  '')})
    with open(args.out, 'w') as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write('\n')
    print('batch_gate: K1 %.1fms / K16 %.1fms -> %+.2f%% '
          '(threshold %.1f%%), dispatches/gulp %.4f -> %.4f, '
          'outputs_identical=%s %s'
          % (t1, t16, regression_pct, args.threshold,
             res['arms']['K1']['dispatches_per_gulp'],
             res['arms']['K16']['dispatches_per_gulp'],
             outputs_ok, 'PASS' if ok else 'FAIL'))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
