#!/usr/bin/env python3
"""Scheduler CLI: plan, lint, and inspect cross-host tenant
placements (bifrost_tpu.scheduler; docs/scheduler.md).

Subcommands::

    bf_sched.py plan fabric.json service.json
        Bin-pack the service spec's tenants across the fabric spec's
        hosts (priority-weighted worst-fit on declared cores), run
        the joint verify_placement pre-gate (verify_fabric +
        verify_service + the BF-E22x placement codes), and print the
        placement table.  Exit 0 when the plan is admissible, 3 on
        any BF-E, 2 when a spec cannot be read.

    bf_sched.py lint fabric.json service.json
        Same gate, diagnostics-only output (no table) — the
        scheduler-level sibling of ``bf_fabric.py lint`` /
        ``bf_serve.py --validate``.

    bf_sched.py status
        One-shot joined per-host × per-tenant health rollup from the
        local proclog tree: every process's ``fabric/health`` row
        merged with its ``service/tenants`` and ``sched/placements``
        rows (the same table ``bf_fabric.py status`` appends and
        like_top renders as ``[sched]``).

Knobs (docs/envvars.md): ``BF_SCHED_REBALANCE_SECS`` death-watch
poll, ``BF_SCHED_DISPLACE_QUOTA_FRAC`` displaced-tenant quota scale,
``BF_SCHED_MAX_REPLACEMENTS`` re-placement event cap,
``BF_SCHED_ARBITER_FRAC`` arbiter quota-transfer fraction.
"""

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _load(fabric_path, service_path):
    from bifrost_tpu.fabric import FabricSpec
    from bifrost_tpu.service import TenantSpec
    spec = FabricSpec.load(fabric_path)
    with open(service_path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or not doc.get('tenants'):
        raise ValueError('service spec must be a JSON object with a '
                         'non-empty "tenants" list')
    tenants = [TenantSpec.coerce(t) for t in doc['tenants']]
    return spec, tenants


def _plan_and_gate(args):
    from bifrost_tpu import scheduler
    from bifrost_tpu.analysis import verify
    try:
        spec, tenants = _load(args.fabric, args.service)
    except (OSError, ValueError) as exc:
        print('bf_sched: cannot read specs: %s' % exc)
        return None, None, None, 2
    try:
        placement = scheduler.plan_placement(
            spec, tenants,
            exclude=[h for h in (args.exclude or '').split(',') if h])
    except scheduler.PlacementError as exc:
        for d in exc.diagnostics:
            print('bf_sched: %r' % d)
        print('bf_sched: placement infeasible (%d error(s))'
              % len(exc.diagnostics))
        return None, None, None, 3
    diags = verify.verify_placement(spec, tenants,
                                    placement.assignments)
    return (spec, tenants, placement, diags)


def cmd_plan(args):
    res = _plan_and_gate(args)
    if isinstance(res[3], int):          # load/plan failure exit code
        return res[3]
    spec, tenants, placement, diags = res
    for d in diags:
        print('bf_sched: %r' % d)
    print('bf_sched: fabric %r: %d host(s), %d tenant(s), '
          '%d diagnostic(s)' % (spec.name, len(spec.hosts),
                                len(tenants), len(diags)))
    for host in sorted(placement.capacity):
        tids = placement.tenants_on(host)
        print('  host %-12s cores=%d demand=%d  %s%s'
              % (host, placement.capacity[host],
                 placement.demand.get(host, 0),
                 ' '.join(tids) or '(idle)',
                 '  OVERSUBSCRIBED' if placement.demand.get(host, 0)
                 > placement.capacity[host] else ''))
    if placement.displaced:
        print('  displaced (quota-scaled, shed by policy): %s'
              % ', '.join(placement.displaced))
    nerr = sum(1 for d in diags if d.is_error)
    print('bf_sched: plan %s' % ('PASS' if nerr == 0
                                 else 'FAIL (%d error(s))' % nerr))
    return 3 if nerr else 0


def cmd_lint(args):
    res = _plan_and_gate(args)
    if isinstance(res[3], int):          # load/plan failure exit code
        return res[3]
    spec, tenants, _placement, diags = res
    from bifrost_tpu.analysis.verify import format_report, errors
    print('bf_sched: fabric %r × %d tenant(s): %d diagnostic(s)'
          % (spec.name, len(tenants), len(diags)))
    print(format_report(diags) if diags else '  (clean)')
    return 3 if errors(diags) else 0


def cmd_status(args):
    from bifrost_tpu import scheduler
    rows = scheduler.joined_rollup()
    print(scheduler.format_rollup(rows))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest='cmd', required=True)
    for name, fn, helptext in (
            ('plan', cmd_plan,
             'bin-pack tenants across hosts and print the table'),
            ('lint', cmd_lint,
             'joint placement pre-gate, diagnostics only')):
        p = sub.add_parser(name, help=helptext)
        p.add_argument('fabric', help='fabric spec JSON')
        p.add_argument('service', help='service spec JSON')
        p.add_argument('--exclude', default='',
                       help='comma-separated hosts to treat as dead')
        p.set_defaults(fn=fn)
    p = sub.add_parser('status',
                       help='joined host × tenant rollup from '
                            'proclogs')
    p.set_defaults(fn=cmd_status)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == '__main__':
    sys.exit(main())
