#!/usr/bin/env python3
"""Repo-invariant lint: every ``BF_*`` environment variable read by
``bifrost_tpu/`` must be documented in ``docs/envvars.md``, and every
documented variable must actually be read somewhere in the repo
(package, tools, bench drivers, or shell scripts) — no phantom knobs,
no undocumented behavior.

    python tools/lint_envvars.py            # report; exit 0/3
    pytest tests/test_tools.py -k envvars   # the tier-1 wiring

Detection: a QUOTED string literal matching ``BF_[A-Z0-9_]+`` in
Python source is an env read (the package's accessors —
``os.environ``, ``_env_int``/``_env_float``, ``EnvVars.get``,
``_force_env`` — all take the name as a string literal; counter/fault
names never start with BF_); in shell scripts any ``$BF_X`` /
``${BF_X...}`` expansion or ``BF_X=`` assignment counts.  Docs side:
any backticked ``BF_*`` token in docs/envvars.md.

Exit codes follow tools/telemetry_diff.py: 0 = clean, 3 = violations.
"""

import argparse
import glob
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: quoted BF_ literal in Python source (an env read by construction in
#: this codebase; docstring prose mentions are unquoted)
_PY_READ = re.compile(r"""['"](BF_[A-Z0-9_]+)['"]""")
#: shell expansion / assignment
_SH_READ = re.compile(r"\$\{?(BF_[A-Z0-9_]+)|^(BF_[A-Z0-9_]+)=",
                      re.MULTILINE)
#: documented token in docs/envvars.md (backticked, possibly with a
#: `=value` suffix or `BF_*` glob-style family references)
_DOC = re.compile(r"`(BF_[A-Z0-9_]+)")

#: variables legitimately not read as literals anywhere scannable
#: (none today; the hook exists for e.g. native-core-only knobs)
ALLOW_UNREAD = set()
#: variables read by the package but intentionally undocumented
#: (none today)
ALLOW_UNDOCUMENTED = set()


def _py_files(*relative_dirs):
    out = []
    for d in relative_dirs:
        out.extend(glob.glob(os.path.join(ROOT, d, '**', '*.py'),
                             recursive=True))
    return [p for p in out if '__pycache__' not in p]


def package_reads():
    """BF_* vars read inside bifrost_tpu/ (the documented-API side of
    the invariant)."""
    vars_ = {}
    for path in _py_files('bifrost_tpu'):
        with open(path, 'r') as f:
            for name in _PY_READ.findall(f.read()):
                vars_.setdefault(name, set()).add(
                    os.path.relpath(path, ROOT))
    return vars_


def repo_reads():
    """BF_* vars read anywhere scannable: the package, tools/, the
    bench drivers, and shell scripts (for the documented->read
    direction; gate knobs live in tools and watch_and_bench.sh)."""
    vars_ = dict(package_reads())
    for path in _py_files('tools', 'tests') + \
            glob.glob(os.path.join(ROOT, 'bench*.py')):
        with open(path, 'r') as f:
            for name in _PY_READ.findall(f.read()):
                vars_.setdefault(name, set()).add(
                    os.path.relpath(path, ROOT))
    for path in glob.glob(os.path.join(ROOT, 'tools', '*.sh')):
        with open(path, 'r') as f:
            for m in _SH_READ.finditer(f.read()):
                name = m.group(1) or m.group(2)
                vars_.setdefault(name, set()).add(
                    os.path.relpath(path, ROOT))
    return vars_


def documented():
    """BF_* vars documented in docs/envvars.md."""
    path = os.path.join(ROOT, 'docs', 'envvars.md')
    with open(path, 'r') as f:
        return set(_DOC.findall(f.read()))


def check():
    """Run both directions; returns a dict report (empty
    'undocumented' + 'phantom' lists = clean)."""
    pkg = package_reads()
    repo = repo_reads()
    docs = documented()
    undocumented = sorted(set(pkg) - docs - ALLOW_UNDOCUMENTED)
    phantom = sorted(docs - set(repo) - ALLOW_UNREAD)
    return {
        'undocumented': [{'var': v, 'read_in': sorted(pkg[v])}
                         for v in undocumented],
        'phantom': phantom,
        'package_vars': len(pkg),
        'documented_vars': len(docs),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('-v', '--verbose', action='store_true',
                    help='list every variable with its read sites')
    args = ap.parse_args()
    report = check()
    if args.verbose:
        for v, sites in sorted(repo_reads().items()):
            print('%-28s %s' % (v, ', '.join(sorted(sites))))
        print()
    for entry in report['undocumented']:
        print('UNDOCUMENTED %-24s read in %s but absent from '
              'docs/envvars.md'
              % (entry['var'], ', '.join(entry['read_in'])))
    for v in report['phantom']:
        print('PHANTOM      %-24s documented in docs/envvars.md but '
              'never read anywhere in the repo' % v)
    bad = bool(report['undocumented'] or report['phantom'])
    print('lint_envvars: %s — %d package var(s), %d documented, '
          '%d undocumented, %d phantom'
          % ('FAIL' if bad else 'OK', report['package_vars'],
             report['documented_vars'], len(report['undocumented']),
             len(report['phantom'])))
    return 3 if bad else 0


if __name__ == '__main__':
    sys.exit(main())
