#!/usr/bin/env python3
"""Compiled-segment gate: ring elision must be free and invisible.

Runs bench_suite config 16 (the config-8 math as SEPARATE
fft/detect/reduce device blocks, run unfused vs under
``BF_SEGMENTS=auto`` vs hand-fused, all at macro K=16 —
bench_suite.bench_segments) in a fresh subprocess pinned to the CPU
backend, and asserts:

- ``outputs_identical``        — the segment arm's output stream is
  byte-identical to the unfused chain (and to the hand-fused arm: the
  compiler builds the SAME composed program a FusedBlock would);
- ``zero_interior_dispatches`` — the fused member blocks issued
  exactly ZERO Python dispatches: inside a segment there are 0
  dispatches and 0 ring handoffs per gulp, and ``block.*.dispatches``
  counts segments, not blocks;
- ``elided``                   — both interior rings were elided
  (``segment.elided_rings == 2``) and registered no span traffic;
- ``throughput_ok``            — the segment arm is no worse than the
  hand-fused macro K=16 arm by more than ``--threshold`` percent,
  judged by the PAIRED-median estimator (per-repetition
  segment/fused wall ratios from the interleaved arms, median over
  reps — the e2e/autotune gates' policy: both arms compile the SAME
  program, and on the 2-core CI host adjacent same-length runs
  spread ±10%, so only paired ratios can certify a 5% bound; eliding
  rings must never cost throughput where it cannot win it).

The arm interleaving / min-of-N noise defenses live inside config 16
itself (per-arm minima, alternating arm order between repetitions).
The full config result is written to the ``--out`` JSON artifact so
bench rounds record the segment path's health next to the throughput
numbers (``BENCH_SEGMENT_${ROUND}.json``).

Exit codes: 0 pass, 3 a gate condition failed, 2 the bench arm failed
to produce a result.  ``tools/watch_and_bench.sh`` runs this after the
macro-gulp batch gate (``BF_SKIP_SEGMENT_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_config16(timeout=1800):
    """One bench_suite --config 16 subprocess on the CPU backend;
    returns its result dict."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    # a configured global mode/batch would skew the labeled arms
    env.pop('BF_SEGMENTS', None)
    env.pop('BF_GULP_BATCH', None)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
         '--config', '16'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and 'arms' in d:
            return d
    raise RuntimeError(
        'config 16 produced no arms result (rc=%d):\n%s\n%s'
        % (out.returncode, out.stdout[-1000:], out.stderr[-1000:]))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default='BENCH_SEGMENT.json',
                    help='artifact path (full config-16 result + '
                         'verdict)')
    ap.add_argument('--threshold', type=float, default=5.0,
                    help='max allowed segment-arm regression vs the '
                         'hand-fused K=16 arm, percent')
    ap.add_argument('--timeout', type=float, default=1800.0,
                    help='bench subprocess timeout in seconds')
    args = ap.parse_args()

    if os.environ.get('BF_SKIP_SEGMENT_GATE', '0') == '1':
        print('segment_gate: skipped (BF_SKIP_SEGMENT_GATE=1)')
        return 0

    try:
        res = run_config16(timeout=args.timeout)
    except (RuntimeError, subprocess.TimeoutExpired) as exc:
        print('segment_gate: bench arm failed: %s' % exc,
              file=sys.stderr)
        return 2

    t_fused = float(res['arms']['fused']['ms_min'])
    t_seg = float(res['arms']['segment']['ms_min'])
    t_un = float(res['arms']['unfused']['ms_min'])
    paired = float(res.get('paired_vs_fused',
                           t_seg / t_fused if t_fused > 0 else 1.0))
    regression_pct = (paired - 1.0) * 100.0
    throughput_ok = regression_pct < args.threshold
    zero_disp = bool(res.get('zero_interior_dispatches'))
    elided = bool(res.get('elided'))
    outputs_ok = bool(res.get('outputs_identical'))
    ok = throughput_ok and zero_disp and elided and outputs_ok
    artifact = dict(res,
                    gate={'paired_vs_fused': round(paired, 4),
                          'regression_vs_fused_pct':
                          round(regression_pct, 2),
                          'threshold_pct': args.threshold,
                          'throughput_ok': throughput_ok,
                          'zero_interior_dispatches': zero_disp,
                          'elided': elided,
                          'outputs_identical': outputs_ok,
                          'pass': ok,
                          'round': os.environ.get('BF_BENCH_ROUND',
                                                  '')})
    with open(args.out, 'w') as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write('\n')
    seg = res['arms']['segment']
    print('segment_gate: unfused %.1fms / segment %.1fms / fused '
          '%.1fms min-of-N; paired median vs fused %+.2f%% '
          '(threshold %.1f%%), member dispatches %d, dispatches/gulp '
          '%.4f, elided rings %d, outputs_identical=%s %s'
          % (t_un, t_seg, t_fused, regression_pct, args.threshold,
             seg['member_dispatches'], seg['dispatches_per_gulp'],
             seg['segment_elided_rings'], outputs_ok,
             'PASS' if ok else 'FAIL'))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
