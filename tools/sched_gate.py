#!/usr/bin/env python3
"""Control-plane gate: the elastic scheduler must survive losing a
host mid-stream without losing bytes, recompiling, or deadlocking.

Runs bench_suite config 20 (bifrost_tpu.scheduler —
docs/scheduler.md: three tenants placed across a 3-host fabric, the
victim tenant in a REAL subprocess acking a durable AckLedger
frontier, SIGKILLed mid-stream) in a fresh subprocess pinned to the
CPU backend, and asserts:

- ``placement_pre_gated``       — the initial plan passed the joint
  ``verify_placement`` pre-gate (no BF-E22x) before launch;
- ``death_detected``            — the head's Membership declared the
  killed host dead;
- ``replacement_automatic``     — the death-watch re-placed the
  victim onto a survivor and it ran to DONE with no operator step;
- ``warm_zero_recompiles``      — the migration was a warm start:
  zero ``fused.plan_builds``, >= 1 plan-depot hit, job flagged warm;
- ``resume_bounded_loss``       — the resume skipped exactly the
  ledger frontier (0 < F < total), counted on
  ``scheduler.resume.skipped_frames``;
- ``byte_exact``                — produced == acked-before-death +
  delivered-after-resume, and the resumed payload equals the source
  tail byte-for-byte;
- ``displaced_sheds_not_deadlocks`` — the lowest-priority tenant on
  the oversubscribed survivor was displaced and SHED by policy
  (counted) while still finishing DONE;
- ``arbiter_restored_slo``      — the cross-tenant arbiter moved
  quota from the donor to the SLO violator and the violator's
  rollup returned under budget within the run;
- ``scheduler_telemetry``       — the ``scheduler`` snapshot section
  recorded the re-placement.

The full config result is written to the ``--out`` JSON artifact
(``SCHED_CHAOS_${ROUND}.json``) so bench rounds record the control
plane's health next to the throughput numbers.

Exit codes: 0 pass, 3 an invariant failed, 2 the drill failed to
run.  ``tools/watch_and_bench.sh`` runs this after the service gate
(``BF_SKIP_SCHED_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_config20(timeout=900):
    """One bench_suite --config 20 subprocess on the CPU backend;
    returns its result dict."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    # configured fault/quota/tuning knobs would skew the scripted
    # drill; ambient fabric identity/state would leak into the
    # drill's own spec; BF_SEGMENTS would swap the warm chain's
    # FusedBlocks for SegmentBlocks (no plan depot -> spurious
    # recompiles)
    for var in ('BF_FAULTS', 'BF_OVERLOAD_POLICY', 'BF_SLO_MS',
                'BF_AUTOTUNE', 'BF_SERVE_MAX_TENANTS',
                'BF_SERVE_WARM', 'BF_SERVE_QUOTA_BURST',
                'BF_GULP_BATCH', 'BF_SYNC_DEPTH', 'BF_SEGMENTS',
                'BF_COMPILE_CACHE', 'BF_FABRIC_STATE',
                'BF_FABRIC_IDENTITY', 'BF_FABRIC_HEARTBEAT_SECS',
                'BF_FABRIC_DEADLINE_SECS', 'BF_SCHED_REBALANCE_SECS',
                'BF_SCHED_DISPLACE_QUOTA_FRAC',
                'BF_SCHED_MAX_REPLACEMENTS', 'BF_SCHED_ARBITER_FRAC'):
        env.pop(var, None)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
         '--config', '20'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and 'invariants' in d:
            return d
    raise RuntimeError(
        'config 20 produced no invariants result (rc=%d):\n%s\n%s'
        % (out.returncode, out.stdout[-1200:], out.stderr[-1200:]))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default='SCHED_CHAOS_cpu.json',
                    help='artifact path for the full config result')
    ap.add_argument('--timeout', type=int, default=900)
    args = ap.parse_args(argv)
    if os.environ.get('BF_SKIP_SCHED_GATE', '0') == '1':
        print('sched_gate: skipped (BF_SKIP_SCHED_GATE=1)')
        return 0
    try:
        res = run_config20(timeout=args.timeout)
    except Exception as exc:
        print('sched_gate: drill failed to run: %s: %s'
              % (type(exc).__name__, exc))
        return 2
    res['round'] = os.environ.get('BF_BENCH_ROUND', '')
    with open(args.out, 'w') as f:
        json.dump(res, f, indent=2, sort_keys=True)
        f.write('\n')
    inv = res.get('invariants', {})
    for name in sorted(inv):
        print('%-30s %s' % (name, 'ok' if inv[name] else 'FAIL'))
    print('ledger: %s' % json.dumps(res.get('ledger', {}),
                                    sort_keys=True))
    print('migration: %s' % json.dumps(res.get('migration', {}),
                                       sort_keys=True))
    ok = bool(inv) and all(inv.values())
    print('sched_gate: %s -> %s' % ('PASS' if ok else 'FAIL',
                                    args.out))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
