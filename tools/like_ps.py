#!/usr/bin/env python3
"""ps-style listing of bifrost_tpu pipelines and their blocks
(reference: tools/like_ps.py)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from bifrost_tpu import proclog  # noqa: E402


def main():
    base = proclog.proclog_dir()
    if not os.path.isdir(base):
        print("No proclog directory at %s" % base)
        return 1
    print('%-8s %-10s %s' % ('PID', 'CORE', 'BLOCK'))
    for pid_s in sorted(os.listdir(base)):
        if not pid_s.isdigit():
            continue
        contents = proclog.load_by_pid(int(pid_s))
        for block, logs in sorted(contents.items()):
            core = logs.get('bind', {}).get('core0', '-')
            print('%-8s %-10s %s' % (pid_s, core, block))
    return 0


if __name__ == '__main__':
    sys.exit(main())
