#!/usr/bin/env python3
"""ps-style listing of running bifrost_tpu pipelines
(reference: tools/like_ps.py).

For every pipeline PID: command line, user, CPU%, memory%, elapsed
time, thread count (via ``ps``), the rings it uses (name, space, size
from the rings/<name> ProcLog geometry entries), and each block with
its read/write ring indices, core binding, and available logs.
"""

import argparse
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from bifrost_tpu import proclog  # noqa: E402
from bifrost_tpu.monitor_utils import (list_pipelines,  # noqa: E402
                                       get_command_line, get_best_size,
                                       ring_geometry, block_rings)


def get_process_details(pid):
    """user/CPU%/mem%/etime/threads via ``ps``
    (reference: like_ps.py:45-77).  Accepts a bare PID or a fabric
    instance entry (``<pid>@<host>.<role>``)."""
    data = {'user': '', 'cpu': 0.0, 'mem': 0.0, 'etime': '00:00',
            'threads': 0}
    try:
        out = subprocess.check_output(
            ['ps', 'o', 'user,pcpu,pmem,etime,nlwp',
             str(proclog.entry_pid(pid) or pid)],
            stderr=subprocess.DEVNULL).decode()
        fields = out.split('\n')[1].split(None, 4)
        data.update({'user': fields[0], 'cpu': float(fields[1]),
                     'mem': float(fields[2]),
                     'etime': fields[3].replace('-', 'd '),
                     'threads': int(fields[4], 10)})
    except (subprocess.CalledProcessError, IndexError, ValueError,
            OSError):
        pass
    return data






def describe_pid(pid):
    """Text description of one pipeline
    (reference: like_ps.py:120-196)."""
    contents = proclog.load_by_pid(pid)
    details = get_process_details(pid)
    cmd = get_command_line(pid)
    if not cmd and not details['user'] and not contents:
        return []
    out = ['PID: %s' % pid,
           '  Command: %s' % cmd,
           '  User: %s' % details['user'],
           '  CPU Usage: %.1f%%' % details['cpu'],
           '  Memory Usage: %.1f%%' % details['mem'],
           '  Elapsed Time: %s' % details['etime'],
           '  Thread Count: %i' % details['threads']]

    geometry = ring_geometry(contents)
    rings = []
    for block, logs in sorted(contents.items()):
        if block.replace(os.sep, '/').startswith('rings'):
            continue
        for ring in sum(block_rings(logs), []):
            if ring not in rings:
                rings.append(ring)

    out.append('  Rings:')
    for i, ring in enumerate(rings):
        dtl = geometry.get(str(ring))
        if dtl and 'stride' in dtl:
            sz, un = get_best_size(
                float(dtl['stride']) *
                max(int(dtl.get('nringlet', 1)), 1))
            out.append('    %i: %s on %s of size %.1f %s'
                       % (i, ring, dtl.get('space', '?'), sz, un))
        else:
            out.append('    %i: %s' % (i, ring))

    out.append('  Blocks:')
    for block, logs in sorted(contents.items()):
        if block.replace(os.sep, '/').startswith('rings'):
            continue
        rins, routs = block_rings(logs)
        core = logs.get('bind', {}).get('core0', None)
        out.append('    %s%s' % (block, '' if core is None
                                 else ' (core %s)' % core))
        if rins:
            out.append('      -> read ring(s): %s'
                       % ' '.join('%i' % rings.index(v) for v in rins
                                  if v in rings))
        if routs:
            out.append('      -> write ring(s): %s'
                       % ' '.join('%i' % rings.index(v) for v in routs
                                  if v in rings))
        if logs:
            out.append('      -> log(s): %s' % ' '.join(sorted(logs)))
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('pid', nargs='*', type=int,
                    help='pipeline PIDs (default: all found)')
    args = ap.parse_args()
    pids = args.pid or list_pipelines()
    if not pids:
        print('No running pipelines found under %s'
              % proclog.proclog_dir())
        return 1
    for pid in pids:
        for line in describe_pid(pid):
            print(line)
    return 0


if __name__ == '__main__':
    sys.exit(main())
