"""Probe the accelerator backend with a hard deadline.

Prints one JSON line {"alive": bool, "init_s": float, "platform": str}
and exits 0 when the backend initializes within the deadline, 3
otherwise.  Used by bench.py's retry loop and by round automation to
decide when the tunneled chip is healthy enough for a capture session.
"""
import json
import os
import sys
import time


def main():
    deadline = float(os.environ.get('BF_PROBE_DEADLINE', '120'))
    t0 = time.time()
    result = {}

    def probe():
        # import bifrost_tpu first: its __init__ honors JAX_PLATFORMS
        # under PJRT plugins that ignore the env var (same reason
        # bench.py imports it before jax) — the probe must gate on the
        # SAME backend the bench will use
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if repo not in sys.path:
            sys.path.insert(0, repo)
        try:
            import bifrost_tpu  # noqa: F401
        except ImportError:
            pass
        import jax
        devs = jax.devices()
        import jax.numpy as jnp
        x = jnp.ones((256, 256), jnp.bfloat16)
        y = float(jnp.sum(x @ x))
        result['platform'] = devs[0].platform
        result['n_devices'] = len(devs)
        result['matmul_ok'] = (y == 256.0 * 256 * 256)

    import threading
    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(deadline)
    init_s = round(time.time() - t0, 1)
    if result.get('platform'):
        print(json.dumps(dict(result, alive=True, init_s=init_s)))
        return 0
    print(json.dumps({'alive': False, 'init_s': init_s}))
    return 3


if __name__ == '__main__':
    sys.exit(main())
