"""Probe the accelerator backend with a hard deadline.

Prints one JSON line {"alive": bool, "init_s": float, "platform": str}
and exits 0 only when the backend BOTH initializes within the deadline
AND passes a bf16 matmul correctness gate (a chip that initializes but
miscomputes must not trigger a bench capture); exits 3 otherwise.
Used by bench.py's retry loop and by round automation to decide when
the tunneled chip is healthy enough for a capture session.
"""
import json
import os
import sys
import time


def main():
    deadline = float(os.environ.get('BF_PROBE_DEADLINE', '120'))
    t0 = time.time()
    result = {}

    def probe():
        # import bifrost_tpu first: its __init__ honors JAX_PLATFORMS
        # under PJRT plugins that ignore the env var (same reason
        # bench.py imports it before jax) — the probe must gate on the
        # SAME backend the bench will use
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if repo not in sys.path:
            sys.path.insert(0, repo)
        try:
            import bifrost_tpu  # noqa: F401
        except ImportError:
            pass
        import jax
        devs = jax.devices()
        import jax.numpy as jnp
        x = jnp.ones((256, 256), jnp.bfloat16)
        # accumulate the check sum in f32: a backend that reduces in
        # bf16 would round 2^24 + 256 terms and fail an exact compare
        # while being perfectly healthy
        y = float(jnp.sum(x @ x, dtype=jnp.float32))
        expected = 256.0 * 256 * 256
        result['platform'] = devs[0].platform
        result['n_devices'] = len(devs)
        result['matmul_ok'] = abs(y - expected) <= 1e-3 * expected

    import threading
    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(deadline)
    init_s = round(time.time() - t0, 1)
    if result.get('platform') and result.get('matmul_ok'):
        print(json.dumps(dict(result, alive=True, init_s=init_s)))
        return 0
    # preserve whatever the probe did collect: a live-but-miscomputing
    # chip (platform set, matmul_ok false) must be distinguishable in
    # watch logs from a 120 s init hang (nothing set)
    print(json.dumps(dict(result, alive=False, init_s=init_s)))
    return 3


if __name__ == '__main__':
    sys.exit(main())
