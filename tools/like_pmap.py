#!/usr/bin/env python3
"""pmap-style memory map of a bifrost_tpu pipeline process
(reference: tools/like_pmap.py).

Reads the pipeline's ring geometry from its rings/<name> ProcLogs and
the process address space from /proc/<pid>/numa_maps, classifies the
memory areas (file-backed vs anonymous, heap/stack/huge/shared/
swapped, NUMA node binding), matches each ring to its best-fit
anonymous area, and reports per-NUMA-node totals plus per-ring mapping
details — the reference tool's full information set.
"""

import argparse
import os
import re
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from bifrost_tpu import proclog  # noqa: E402
from bifrost_tpu.monitor_utils import (get_best_size,  # noqa: E402
                                       ring_geometry)


_NODE_RE = re.compile(r'^N(\d+)=(\d+)$')


def _page_sizes():
    page = 4096
    huge = 2 * 1024 * 1024
    try:
        page = int(subprocess.check_output(['getconf', 'PAGESIZE']), 10)
    except (subprocess.CalledProcessError, ValueError, OSError):
        pass
    try:
        with open('/proc/meminfo') as f:
            for line in f:
                if line.startswith('Hugepagesize:'):
                    huge = int(line.split()[1], 10) * 1024
                    break
    except (OSError, ValueError):
        pass
    return page, huge


def load_numa_maps(pid, page, huge_page):
    """Parse /proc/<pid>/numa_maps into file-backed and anonymous area
    dicts (reference: like_pmap.py:84-155)."""
    files, areas = {}, {}
    try:
        with open('/proc/%d/numa_maps' % pid) as fh:
            lines = fh.read().split('\n')
    except OSError:
        return files, areas
    for line in lines:
        is_file = line.find('file=') != -1
        is_anon = line.find('anon=') != -1
        if not (is_file or is_anon):
            continue
        tokens = line.split()
        if not tokens:
            continue
        addr = tokens[0]
        huge = 'huge' in line
        scale = huge_page if huge else page
        # pages may be spread over several NUMA nodes (N0=.. N1=..):
        # total them for the size; bind the area to its largest node.
        # Swapped-out pages appear as swapcache=<pages>.
        node_pages, swap_pages = {}, 0
        for tok in tokens[1:]:
            m = _NODE_RE.match(tok)
            if m:
                node_pages[int(m.group(1))] = \
                    node_pages.get(int(m.group(1)), 0) + \
                    int(m.group(2), 10)
            elif tok.startswith('swapcache='):
                try:
                    swap_pages = int(tok.split('=', 1)[1], 10)
                except ValueError:
                    pass
        if not node_pages and not swap_pages:
            continue
        entry = {
            # a fully swapped-out area has no resident N<node>= counts;
            # size it by its swapcache pages and park it on node -1
            'size': (sum(node_pages.values()) or swap_pages) * scale,
            'node': max(node_pages, key=node_pages.get)
                    if node_pages else -1,
            'huge': huge,
            'heap': 'heap' in line,
            'stack': 'stack' in line,
            'shared': 'mapmax=' in line,
            'swapped': swap_pages > 0,
            'swapsize': swap_pages * scale,
        }
        (files if is_file else areas)[addr] = entry
    return files, areas


def load_rings(pid):
    """Ring geometry from the rings/<name> ProcLogs."""
    return ring_geometry(proclog.load_by_pid(pid))


def node_totals(table):
    counts, sizes = {}, {}
    for entry in table.values():
        node = entry['node']
        counts[node] = counts.get(node, 0) + 1
        sizes[node] = sizes.get(node, 0) + entry['size']
    return counts, sizes


def _area_summary(label, table):
    out = ['%s:' % label,
           '  Total: %i' % len(table),
           '  Heap: %i' % sum(e['heap'] for e in table.values()),
           '  Stack: %i' % sum(e['stack'] for e in table.values()),
           '  Shared: %i' % sum(e['shared'] for e in table.values()),
           '  Swapped: %i' % sum(e['swapped'] for e in table.values())]
    counts, sizes = node_totals(table)
    for node in sorted(counts):
        out.append('  NUMA Node %i:' % node)
        out.append('    Count: %i' % counts[node])
        out.append('    Size: %.3f %s' % get_best_size(sizes[node]))
    return out


def report(pid):
    page, huge = _page_sizes()
    rings = load_rings(pid)
    files, areas = load_numa_maps(pid, page, huge)

    # best-fit ring -> anonymous area matching
    # (reference: like_pmap.py:156-168)
    matched = []
    for name, dtl in rings.items():
        stride = float(dtl.get('stride', 0)) * \
            max(int(dtl.get('nringlet', 1)), 1)
        dtl['bytes'] = stride
        dtl['addr'] = None
        if dtl.get('space') not in (None, 'system', 'tpu_host'):
            continue     # device-resident; not in the host map
        best, metric = None, float('inf')
        for addr, entry in areas.items():
            diff = abs(entry['size'] - stride)
            if diff < metric:
                best, metric = addr, diff
        dtl['addr'] = best
        if best is not None:
            matched.append(best)

    out = ['Rings: %i' % len(rings)]
    out += _area_summary('File Backed Memory Areas', files)
    out += _area_summary('Anonymous Memory Areas', areas)
    out.append('')
    out.append('Ring Mappings:')
    for name in sorted(rings):
        dtl = rings[name]
        out.append('  %s' % name)
        out.append('    Space: %s' % dtl.get('space', '?'))
        out.append('    Size: %.3f %s' % get_best_size(dtl['bytes']))
        if dtl.get('space') not in (None, 'system', 'tpu_host'):
            out.append('    Area: (device-resident; not in the host '
                       'address space)')
            continue
        area = areas.get(dtl.get('addr'))
        if area is None:
            out.append('    Area: Unknown')
            continue
        diff = abs(area['size'] - dtl['bytes'])
        status = ' ???' if diff > 0.5 * huge else ''
        out.append('    Area: %s%s' % (dtl['addr'], status))
        sv, su = get_best_size(area['size'])
        if diff:
            dv, du = get_best_size(diff)
            out.append('      Size: %.3f %s (within %.3f %s)'
                       % (sv, su, dv, du))
        else:
            out.append('      Size: %.3f %s' % (sv, su))
        out.append('      Node: %i' % area['node'])
        out.append('      Attributes:')
        out.append('        Huge? %s' % area['huge'])
        out.append('        Heap? %s' % area['heap'])
        out.append('        Stack? %s' % area['stack'])
        out.append('        Shared? %s' % area['shared'])
        out.append('      Swap Status:')
        out.append('        Swapped? %s' % area['swapped'])
        if area['swapped'] and area['size']:
            out.append('        Swap Fraction: %.1f%%'
                       % (100.0 * area['swapsize'] / area['size']))
    out.append('')
    other = sum(e['size'] for a, e in areas.items() if a not in matched)
    out.append('Other Non-Ring Areas:')
    out.append('  Size: %.3f %s' % get_best_size(other))
    out.append('')
    out.append('File Backed Areas:')
    out.append('  Size: %.3f %s'
               % get_best_size(sum(e['size'] for e in files.values())))
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('pid', nargs='?', type=int,
                    help='pipeline PID (default: first found)')
    args = ap.parse_args()
    pid = args.pid
    if pid is None:
        base = proclog.proclog_dir()
        pids = sorted(int(p) for p in os.listdir(base)
                      if p.isdigit()) if os.path.isdir(base) else []
        if not pids:
            print('No running pipelines found under %s' % base)
            return 1
        pid = pids[0]
    for line in report(pid):
        print(line)
    return 0


if __name__ == '__main__':
    sys.exit(main())
