#!/usr/bin/env python3
"""pmap-style memory map of bifrost_tpu pipeline processes
(reference: tools/like_pmap.py): per-pipeline ring/buffer summary from
/proc/<pid>/status plus the ProcLog tree."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from bifrost_tpu import proclog  # noqa: E402


def _proc_mem(pid):
    out = {}
    try:
        with open('/proc/%d/status' % pid) as f:
            for line in f:
                if line.startswith(('VmRSS', 'VmSize', 'VmHWM')):
                    k, v = line.split(':', 1)
                    out[k] = v.strip()
    except OSError:
        pass
    return out


def main():
    base = proclog.proclog_dir()
    if not os.path.isdir(base):
        print("No proclog directory at %s" % base)
        return 1
    for pid_s in sorted(os.listdir(base)):
        if not pid_s.isdigit():
            continue
        pid = int(pid_s)
        mem = _proc_mem(pid)
        print("pid %d  %s" % (pid, '  '.join('%s=%s' % kv
                                             for kv in mem.items())))
        contents = proclog.load_by_pid(pid)
        rings = set()
        for block, logs in sorted(contents.items()):
            for log in ('in', 'out'):
                d = logs.get(log, {})
                for i in range(d.get('nring', 0)):
                    if 'ring%i' % i in d:
                        rings.add(d['ring%i' % i])
        for r in sorted(rings):
            print("   ring %s" % r)
    return 0


if __name__ == '__main__':
    sys.exit(main())
