#!/usr/bin/env python3
"""FX-correlator flagship gate: the quantized X-engine must WIN and the
whole chain must be EXACT — this publishes the BENCH_FXCORR_*.json
artifact series and a mesh-scaling row into the MULTICHIP_*.json glob.

Runs bench_suite config 19 (ci8 stations -> F -> requantize -> X ->
accumulate; bench_suite.bench_fxcorr) in a fresh subprocess pinned to
the CPU backend with ``--xla_force_host_platform_device_count=8``, and
asserts:

- ``quant_beats_f32``         — the X-engine race winner at the int8
  accuracy class beats the complex64 XLA baseline in the engine
  microbench (on the CPU gate host that is typically the bf16 plane
  GEMM; on MXU hosts the exact int8 kernels — measured, never
  asserted);
- ``oracle_identical``        — every arm (f32 / quant / segment) is
  BYTE-identical to the sequential oracle: eager F + quantize, then an
  int64 numpy X step.  The integer visibilities are exactly
  representable in complex64, so no arm gets a tolerance;
- ``zero_member_dispatches``  — under BF_SEGMENTS=force the
  capture->F->quantize->X->accumulate chain compiled into ONE segment
  and the member blocks dispatched exactly ZERO times;
- ``deterministic``           — the three arms' output streams are
  byte-identical to each other.

The mesh arm (stateful CorrelateBlock striped over the 8-device mesh,
psum vs the corner-turn collective) must byte-match the single-device
run when it ran; its wall ratio is recorded but NOT gated (virtual
host-platform devices share cores — the real-chip round overwrites the
row).  Its result also lands as ``MULTICHIP_${BF_BENCH_ROUND}_fxcorr
.json`` so the mesh artifact series gains the baselines x channels/s
per chip row next to config 11's.

Exit codes: 0 pass, 3 a gate condition failed, 2 the bench failed to
produce a result.  ``tools/watch_and_bench.sh`` runs this after the
mesh gate (``BF_SKIP_FXCORR_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_DEVICES = 8


def run_config19(timeout=1800):
    """One bench_suite --config 19 subprocess on an 8-device
    host-platform mesh; returns its result dict."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    flags = env.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        env['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=%d'
            % N_DEVICES).strip()
    # a configured global batch/donate would skew the arm comparison
    env.pop('BF_GULP_BATCH', None)
    env.pop('BF_DONATE', None)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
         '--config', '19'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and 'xengine' in d:
            return d
        if isinstance(d, dict) and d.get('error'):
            raise RuntimeError('config 19 failed: %s' % d['error'])
    raise RuntimeError(
        'config 19 produced no result (rc=%d):\n%s\n%s'
        % (out.returncode, out.stdout[-1000:], out.stderr[-1000:]))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    round_ = os.environ.get('BF_BENCH_ROUND', 'cpu')
    ap.add_argument('--out', default='BENCH_FXCORR_%s.json' % round_,
                    help='artifact path (full config-19 result + '
                         'verdict)')
    ap.add_argument('--mesh-out',
                    default='MULTICHIP_%s_fxcorr.json' % round_,
                    help='mesh-scaling row artifact (written only '
                         'when the mesh arm ran)')
    ap.add_argument('--timeout', type=float, default=1800.0,
                    help='bench subprocess timeout in seconds')
    args = ap.parse_args()

    try:
        res = run_config19(timeout=args.timeout)
    except (RuntimeError, subprocess.TimeoutExpired) as exc:
        print('fxcorr_gate: bench failed: %s' % exc, file=sys.stderr)
        return 2

    quant_ok = bool(res.get('quant_beats_f32'))
    oracle_ok = bool(res.get('oracle_identical'))
    seg_ok = bool(res.get('zero_member_dispatches'))
    det_ok = bool(res.get('deterministic'))
    mesh = res.get('mesh')
    # gated only when the arm ran: a 1-device host legitimately skips
    mesh_ok = bool(mesh.get('outputs_match')) if mesh else True
    ok = quant_ok and oracle_ok and seg_ok and det_ok and mesh_ok
    artifact = dict(res,
                    gate={'quant_beats_f32': quant_ok,
                          'oracle_identical': oracle_ok,
                          'zero_member_dispatches': seg_ok,
                          'deterministic': det_ok,
                          'mesh_outputs_match': mesh_ok,
                          'mesh_arm_ran': bool(mesh),
                          'pass': ok,
                          'round': os.environ.get('BF_BENCH_ROUND',
                                                  '')})
    with open(args.out, 'w') as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write('\n')
    if mesh:
        row = dict(mesh,
                   config='FX correlator mesh arm (bench_suite '
                          'config 19): stateful CorrelateBlock '
                          'striped over the device mesh, psum vs '
                          'corner-turn collective',
                   config_id=19,
                   gate={'outputs_match': mesh_ok,
                         'ratio_gated': False,
                         'pass': mesh_ok,
                         'round': os.environ.get('BF_BENCH_ROUND',
                                                 '')})
        with open(args.mesh_out, 'w') as f:
            json.dump(row, f, indent=1, sort_keys=True)
            f.write('\n')
    xe = res.get('xengine', {})
    print('fxcorr_gate: winner %s %.1f GOP/s vs xla %.1f GOP/s, '
          'quant_beats_f32=%s oracle_identical=%s '
          'zero_member_dispatches=%s deterministic=%s mesh=%s %s'
          % (xe.get('winner'), xe.get('gops_per_s', -1),
             xe.get('xla_gops_per_s', -1), quant_ok, oracle_ok,
             seg_ok, det_ok,
             ('match' if mesh_ok else 'MISMATCH') if mesh
             else 'skipped',
             'PASS' if ok else 'FAIL'))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
