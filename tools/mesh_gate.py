#!/usr/bin/env python3
"""Mesh-resident pipeline gate: the sharded arm must be CORRECT and
actually mesh-resident — this revives the MULTICHIP_*.json artifact
series as a measured pipeline benchmark (it was a dryrun before PR 6).

Runs bench_suite config 11 (the config-8-style chain, single-device vs
sharded over an 8-device mesh — bench_suite.bench_mesh_pipeline) in a
fresh subprocess pinned to the CPU backend with
``--xla_force_host_platform_device_count=8``, and asserts:

- ``outputs_match``  — the sharded arm's output stream equals the
  single-device arm within float tolerance (one stream, N chips wide,
  same answer);
- ``mesh_engaged``   — sharded spans actually flowed through the rings
  (``mesh.sharded_commits`` > 0) and the fused block ran macro-gulp
  batched under the mesh rather than silently falling back;
- ``zero_reshard``   — every analyzed mesh plan compiled
  collective-free (BF_MESH_HLO_STATS) and steady-state gulps needed no
  relayout: chained mesh blocks exchanged spans with zero reshards.

The sharded/single-device wall ratio is recorded but NOT gated: the 8
'devices' of a host-platform mesh share the same physical cores, so
the virtual arms measure correctness and dispatch overhead, not ICI
scaling.  Real-chip rounds overwrite the artifact with measured
ratios.

The full config result lands in ``--out`` (default
MULTICHIP_${BF_BENCH_ROUND}.json when the round is set).

Exit codes: 0 pass, 3 a gate condition failed, 2 the bench arm failed
to produce a result.  ``tools/watch_and_bench.sh`` runs this after the
bridge gate (``BF_SKIP_MESH_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_DEVICES = 8


def run_config11(timeout=1800):
    """One bench_suite --config 11 subprocess on an 8-device
    host-platform mesh; returns its result dict."""
    env = dict(os.environ, JAX_PLATFORMS='cpu', BF_MESH_HLO_STATS='1')
    flags = env.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        env['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=%d'
            % N_DEVICES).strip()
    # a configured global batch/donate would skew the arm comparison
    env.pop('BF_GULP_BATCH', None)
    env.pop('BF_DONATE', None)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
         '--config', '11'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and 'arms' in d:
            return d
        if isinstance(d, dict) and d.get('skipped'):
            raise RuntimeError('config 11 skipped: %s' % d)
    raise RuntimeError(
        'config 11 produced no arms result (rc=%d):\n%s\n%s'
        % (out.returncode, out.stdout[-1000:], out.stderr[-1000:]))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    round_ = os.environ.get('BF_BENCH_ROUND', 'cpu')
    ap.add_argument('--out', default='MULTICHIP_%s.json' % round_,
                    help='artifact path (full config-11 result + '
                         'verdict)')
    ap.add_argument('--timeout', type=float, default=1800.0,
                    help='bench subprocess timeout in seconds')
    args = ap.parse_args()

    try:
        res = run_config11(timeout=args.timeout)
    except (RuntimeError, subprocess.TimeoutExpired) as exc:
        print('mesh_gate: bench arm failed: %s' % exc, file=sys.stderr)
        return 2

    outputs_ok = bool(res.get('outputs_match'))
    engaged_ok = bool(res.get('mesh_engaged'))
    reshard_ok = bool(res.get('zero_reshard'))
    ok = outputs_ok and engaged_ok and reshard_ok
    ratio = res.get('value')
    artifact = dict(res,
                    gate={'outputs_match': outputs_ok,
                          'mesh_engaged': engaged_ok,
                          'zero_reshard': reshard_ok,
                          'wall_ratio_sharded_vs_single': ratio,
                          'ratio_gated': False,
                          'pass': ok,
                          'round': os.environ.get('BF_BENCH_ROUND',
                                                  '')})
    with open(args.out, 'w') as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write('\n')
    arms = res.get('arms', {})
    print('mesh_gate: single %.1fms / sharded %.1fms (ratio %.2fx, '
          'informational), outputs_match=%s mesh_engaged=%s '
          'zero_reshard=%s %s'
          % (arms.get('single', {}).get('ms_min', -1),
             arms.get('sharded', {}).get('ms_min', -1),
             ratio if isinstance(ratio, (int, float)) else -1,
             outputs_ok, engaged_ok, reshard_ok,
             'PASS' if ok else 'FAIL'))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
