#!/usr/bin/env python3
"""bf_serve: run N tenant pipelines as one multi-tenant service
(bifrost_tpu.service — docs/service.md).

    python tools/bf_serve.py spec.json [--duration S] [--validate]
    python tools/bf_serve.py spec.json --validate     # static only

The spec file is JSON::

    {"max_tenants": 8,                     # optional
     "tenants": [
       {"id": "replay0",
        "source": {"kind": "replay", "basenames": ["rec/pulses"],
                   "gulp_nframe": 256, "loop": 4,
                   "restamp": true},
        "priority": 2, "ncores": 2,
        "quota_bytes_per_s": 50e6, "quota_policy": "pace",
        "slo_ms": 250,
        "sink": "discard"},
       {"id": "cap0",
        "source": {"kind": "udp", "port": 12345, "nsrc": 4,
                   "payload": 4096, "buffer_ntime": 512},
        "gulp_nframe": 256, "overload_policy": "drop_oldest",
        "quota_bytes_per_s": 100e6}
     ]}

Source kinds: ``replay`` (blocks/serialize.py recordings, looped with
per-loop renumbering + trace restamp), ``file`` (flat binary),
``synthetic`` (paced deterministic stream), ``udp`` (live packet
capture — the service owns the capture pump).  Sinks: ``discard``
(default) or ``serialize`` (re-record the admitted stream).

``--validate`` runs the static service verifier
(``analysis.verify.verify_service``: BF-E210 duplicate tenant /
BF-E211 quota below one gulp / BF-W212 core oversubscription), builds
every tenant pipeline, and lints each with the pipeline verifier —
without running anything.  Exit 3 on any BF-E.

Without ``--validate`` the service runs until every tenant finishes
(or ``--duration`` elapses), then prints the final per-tenant rollup
(the same dict ``telemetry.snapshot()['tenants']`` carries) as JSON.
Watch it live in another terminal: ``tools/like_top.py`` renders the
``[tenants]`` pane from the ``service/tenants`` ProcLog.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from bifrost_tpu import service  # noqa: E402
from bifrost_tpu.analysis import verify  # noqa: E402


def load_spec(path):
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or not doc.get('tenants'):
        raise SystemExit('bf_serve: spec must be a JSON object with a '
                         'non-empty "tenants" list')
    specs = [service.TenantSpec.coerce(t) for t in doc['tenants']]
    return doc, specs


def validate(doc, specs):
    diags = verify.verify_service(specs)
    for d in diags:
        print('bf_serve: %r' % d)
    nerr = sum(1 for d in diags if d.is_error)
    if nerr:
        print('bf_serve: %d spec error(s); not building' % nerr)
        return 3
    mgr = service.JobManager(
        max_tenants=int(doc.get('max_tenants', 0) or
                        max(len(specs), 8)),
        warm=False)
    total_err = 0
    try:
        for s in specs:
            try:
                job = mgr.submit(s)
            except service.ServiceError as exc:
                # a spec-level admission refusal (capacity, duplicate)
                # is a lint finding here, not a crash
                total_err += 1
                print('bf_serve: tenant %-16s REJECTED: %s'
                      % (s.id, exc))
                continue
            pdiags = job.pipeline.validate()
            errs = [d for d in pdiags if d.is_error]
            total_err += len(errs)
            print('bf_serve: tenant %-16s %d diagnostic(s), '
                  '%d error(s)' % (s.id, len(pdiags), len(errs)))
            for d in pdiags:
                if d.severity != 'info':
                    print('    %r' % d)
    finally:
        # release build side effects (a 'udp' tenant binds its
        # capture port at build time) — validation must leave nothing
        # behind
        for job in mgr.jobs():
            try:
                job.stop(0)
            except Exception:
                pass
    print('bf_serve: validate %s (%d tenant(s), %d error(s))'
          % ('PASS' if total_err == 0 else 'FAIL', len(specs),
             total_err))
    return 0 if total_err == 0 else 3


def main():
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument('spec', help='service spec JSON file')
    ap.add_argument('--duration', type=float, default=None,
                    help='stop the service after this many seconds '
                         '(default: run until every tenant finishes)')
    ap.add_argument('--validate', action='store_true',
                    help='static spec + pipeline verification only')
    args = ap.parse_args()

    doc, specs = load_spec(args.spec)
    if args.validate:
        return validate(doc, specs)

    mgr = service.JobManager(
        max_tenants=int(doc.get('max_tenants', 0) or
                        max(len(specs), 8)))
    for s in specs:
        try:
            job = mgr.submit(s)
        except service.ServiceError as exc:
            print('bf_serve: tenant %r rejected: %s' % (s.id, exc))
            mgr.shutdown()
            return 3
        print('bf_serve: admitted tenant %-16s cores=%s warm=%s'
              % (s.id, job.cores, 'yes' if job.warm else 'no'))
    mgr.start()
    try:
        if args.duration:
            deadline = time.monotonic() + args.duration
            while time.monotonic() < deadline and any(
                    j.state == 'RUNNING' for j in mgr.jobs()):
                time.sleep(0.25)
        else:
            mgr.wait()
    except KeyboardInterrupt:
        print('bf_serve: interrupted; shutting tenants down')
    finally:
        mgr.shutdown()
    out = service.telemetry_section()
    print(json.dumps(out, indent=1, sort_keys=True, default=str))
    # joined host × tenant rollup (docs/scheduler.md): when this
    # service runs beside a fabric launcher or scheduler, show the
    # merged per-host table too — the same one bf_fabric.py status /
    # bf_sched.py status print
    try:
        from bifrost_tpu.scheduler import joined_rollup, format_rollup
        joined = joined_rollup()
        if any(r['tenants'] for r in joined):
            print('bf_serve: host × tenant rollup:')
            print(format_rollup(joined))
    except Exception:
        pass
    failed = [tid for tid, d in out.items()
              if d.get('state') == 'FAILED']
    if failed:
        print('bf_serve: %d tenant(s) FAILED: %s'
              % (len(failed), ', '.join(failed)))
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
