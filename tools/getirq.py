#!/usr/bin/env python3
"""Show the CPU affinity of a NIC's IRQs (reference: tools/getirq).

Usage: getirq.py <interface>
"""

import sys


def irqs_for(iface):
    out = []
    with open('/proc/interrupts') as f:
        for line in f:
            if iface in line:
                irq = line.split(':', 1)[0].strip()
                if irq.isdigit():
                    out.append(int(irq))
    return out


def main():
    if len(sys.argv) != 2:
        print(__doc__)
        return 1
    iface = sys.argv[1]
    found = irqs_for(iface)
    if not found:
        print("No IRQs found for interface %r" % iface)
        return 1
    for irq in found:
        try:
            with open('/proc/irq/%d/smp_affinity_list' % irq) as f:
                aff = f.read().strip()
        except OSError:
            aff = '?'
        print("irq %d -> cpus %s" % (irq, aff))
    return 0


if __name__ == '__main__':
    sys.exit(main())
