#!/usr/bin/env python3
"""Chaos/soak driver for the overload-resilience layer
(docs/robustness.md "Overload & degradation").

Drives a bridged two-process pipeline (paced source -> drop_oldest
ring -> BridgeSink -> chaos TCP proxy -> BridgeSource -> sink) through
a scripted fault schedule — slow-consumer overload burst (the proxy
stops forwarding, so credit stalls and counted shedding engages),
connection kill/redial (receiver 'restart': jittered sender redial +
retransmit, receiver re-accept + resume), and a deterministic
mid-stream block failure (testing/faults.py) absorbed by the restart
policy — then audits the invariants:

- no deadlock (both processes exit inside the timeout);
- no silent loss (produced == delivered + shed, byte-exact across the
  ring and bridge shed ledgers);
- health traverses OK -> SHEDDING -> ... -> OK;
- capture-to-exit p99 stays under ``BF_SLO_MS`` while shedding;
- the kill recovers (reconnects counted both sides, clean MSG_END)
  and the injected failure costs exactly one supervisor restart.

The machinery lives in ``bench_suite.bench_chaos_soak`` (config 15 —
what ``tools/chaos_gate.py`` gates in CI); this CLI exposes the
schedule knobs for interactive chaos drills::

    python tools/chaos_soak.py                     # default drill
    python tools/chaos_soak.py --secs 60 --tick-ms 2   # longer soak
    BF_CHAOS_SEED=7 python tools/chaos_soak.py     # jitter the phases

Exit codes: 0 every invariant held, 3 an invariant failed, 2 the
drill itself could not run (matches tools/telemetry_diff.py).
"""

import argparse
import json
import os
import random
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument('--secs', type=float, default=None,
                    help='approximate streaming seconds (scales the '
                         'gulp count at --tick-ms pacing)')
    ap.add_argument('--tick-ms', type=float, default=5.0,
                    help='source pacing per gulp (default 5 ms)')
    ap.add_argument('--pause-at', type=float, default=2.0,
                    help='overload burst start (s; default 2)')
    ap.add_argument('--pause-secs', type=float, default=3.0,
                    help='overload burst length (s; default 3)')
    ap.add_argument('--kill-at', type=float, default=6.5,
                    help='connection kill time (s; default 6.5)')
    ap.add_argument('--slo-ms', type=float, default=5000.0,
                    help='BF_SLO_MS budget the p99 invariant checks')
    ap.add_argument('--out', default=None,
                    help='write the full result JSON here')
    args = ap.parse_args(argv)

    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import bench_suite

    kwargs = dict(tick_ms=args.tick_ms, pause_at=args.pause_at,
                  pause_secs=args.pause_secs, kill_at=args.kill_at,
                  slo_ms=args.slo_ms)
    if args.secs:
        # 3 sources share the stream: size each for ~secs/3 of pacing
        kwargs['ngulp'] = max(int(args.secs * 1e3 / args.tick_ms / 3),
                              50)
    seed = os.environ.get('BF_CHAOS_SEED', '').strip()
    if seed:
        # jittered schedule: same invariants, different interleavings
        rng = random.Random(int(seed))
        kwargs['pause_at'] = args.pause_at * rng.uniform(0.7, 1.4)
        kwargs['pause_secs'] = args.pause_secs * rng.uniform(0.7, 1.3)
        kwargs['kill_at'] = (kwargs['pause_at'] + kwargs['pause_secs']
                             + rng.uniform(0.5, 2.5))

    try:
        res = bench_suite.bench_chaos_soak(**kwargs)
    except Exception as exc:
        print('chaos_soak: drill failed to run: %s: %s'
              % (type(exc).__name__, exc))
        return 2
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(res, f, indent=2, sort_keys=True)
    print(json.dumps(res['invariants'], indent=2, sort_keys=True))
    print('ledger: %s' % json.dumps(res['ledger'], sort_keys=True))
    print('chaos_soak: %s (%.2f%% of produced bytes shed, all '
          'counted)' % ('PASS' if res['pass'] else 'FAIL',
                        res['value']))
    return 0 if res['pass'] else 3


if __name__ == '__main__':
    sys.exit(main())
