#!/usr/bin/env python3
"""Auto-tune convergence gate: the closed-loop controller must find
the hand-tuned configuration on its own.

Runs bench_suite config 14 (bench_suite.bench_autotune) in a fresh
subprocess pinned to the CPU backend — a deliberately de-tuned cold
start (K=1, sync_depth=1) tuned by `bifrost_tpu.autotune` against the
hand-tuned config-9 optimum (gulp_batch=16, sync_depth=4) — and
asserts the acceptance triple (docs/autotune.md):

- ``converged_within`` — the tuned arm's min-of-N wall time closes to
  within ``--threshold`` percent of the hand-tuned arm (the controller
  found the amortized regime without an operator);
- ``outputs_identical`` — every arm (de-tuned, tuned, hand-tuned,
  controller-overhead) produced byte-identical output streams: a
  retune must never change the data;
- ``overhead_ok`` — with every knob ceiling pinned (no retunes can
  fire) the running controller costs at most ``--overhead`` percent
  on the config-8 chain, measured by ``tools/obs_overhead.py --stack
  autotune`` in fresh subprocesses per arm (the converged controller
  is effectively free);
- ``controller_acted`` — the warm-up climb actually retuned (a gate
  that passes because the controller never ran proves nothing).

The converged knob values land in the artifact (``converged_knobs``),
so every bench round records WHERE the controller landed next to how
fast it got there.  Noise defenses (per-arm minima, alternating arm
order, warm-up rounds sharing a freeze profile) live inside config 14.

Exit codes: 0 pass, 3 a gate condition failed, 2 the bench arm failed
to produce a result.  ``tools/watch_and_bench.sh`` runs this after the
batch gate (``BF_SKIP_TUNE_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_config14(timeout=1800):
    """One bench_suite --config 14 subprocess on the CPU backend;
    returns its result dict."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    # ambient tuning config would skew the arms
    for var in ('BF_GULP_BATCH', 'BF_SYNC_DEPTH', 'BF_AUTOTUNE',
                'BF_AUTOTUNE_PROFILE', 'BF_AUTOTUNE_INTERVAL',
                'BF_AUTOTUNE_COOLDOWN'):
        env.pop(var, None)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
         '--config', '14'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and 'arms' in d:
            return d
    raise RuntimeError(
        'config 14 produced no arms result (rc=%d):\n%s\n%s'
        % (out.returncode, out.stdout[-1000:], out.stderr[-1000:]))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default='BENCH_TUNE.json',
                    help='artifact path (full config-14 result + '
                         'verdict)')
    ap.add_argument('--threshold', type=float, default=5.0,
                    help='max allowed tuned-arm gap to the hand-tuned '
                         'optimum, percent')
    ap.add_argument('--overhead', type=float, default=2.0,
                    help='max allowed converged-controller overhead '
                         'on the hand-tuned arm, percent')
    ap.add_argument('--timeout', type=float, default=1800.0,
                    help='bench subprocess timeout in seconds')
    args = ap.parse_args()

    try:
        res = run_config14(timeout=args.timeout)
    except (RuntimeError, subprocess.TimeoutExpired) as exc:
        print('autotune_gate: bench arm failed: %s' % exc,
              file=sys.stderr)
        return 2

    # the converged-overhead criterion is judged on the config-8
    # chain via tools/obs_overhead.py --stack autotune: fresh
    # subprocesses per arm, per-arm minima, alternating order — the
    # in-process config-14 arms are too short (~250ms) for their
    # paired median to resolve a 2% bound (recorded in the artifact
    # as converged_overhead_pct_informational)
    ov_out = os.path.join(tempfile.mkdtemp(prefix='bf_tune_gate_'),
                          'overhead.json')
    ov = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'tools',
                                      'obs_overhead.py'),
         '--stack', 'autotune', '--threshold', str(args.overhead),
         '--reps', '3', '--out', ov_out],
        capture_output=True, text=True, cwd=ROOT,
        timeout=args.timeout)
    try:
        with open(ov_out) as f:
            ovres = json.load(f)
        overhead = float(ovres.get('overhead_pct', 1e9))
    except (OSError, ValueError):
        print('autotune_gate: overhead arm failed (rc=%d):\n%s'
              % (ov.returncode, ov.stderr[-1000:]), file=sys.stderr)
        return 2
    res['converged_overhead_pct'] = overhead
    res['overhead_samples_ms'] = {
        'off': ovres.get('spans_disabled_ms'),
        'on': ovres.get('spans_enabled_ms')}

    gap = float(res.get('gap_to_hand_tuned_pct', 1e9))
    converged_ok = gap <= args.threshold
    overhead_ok = overhead <= args.overhead
    outputs_ok = bool(res.get('outputs_identical'))
    acted = bool(res.get('controller_acted'))
    ok = converged_ok and overhead_ok and outputs_ok and acted
    artifact = dict(res,
                    gate={'gap_pct': round(gap, 2),
                          'threshold_pct': args.threshold,
                          'converged_within': converged_ok,
                          'overhead_pct': round(overhead, 2),
                          'overhead_threshold_pct': args.overhead,
                          'overhead_ok': overhead_ok,
                          'outputs_identical': outputs_ok,
                          'controller_acted': acted,
                          'pass': ok,
                          'round': os.environ.get('BF_BENCH_ROUND',
                                                  '')})
    with open(args.out, 'w') as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write('\n')
    print('autotune_gate: detuned %.1fms -> tuned %.1fms, hand '
          '%.1fms (gap %+.2f%%, threshold %.1f%%), converged '
          'overhead %+.2f%% (<=%.1f%%), knobs %s, '
          'outputs_identical=%s %s'
          % (res['arms']['detuned']['ms_min'],
             res['arms']['tuned']['ms_min'],
             res['arms']['hand']['ms_min'], gap, args.threshold,
             overhead, args.overhead,
             json.dumps(res.get('converged_knobs', {}),
                        sort_keys=True),
             outputs_ok, 'PASS' if ok else 'FAIL'))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
