#!/usr/bin/env python3
"""Pin a NIC's IRQs to a CPU (reference: tools/setirq).

Usage: setirq.py <interface> <cpu>   (requires root)
"""

import sys

from getirq import irqs_for  # noqa: E402


def main():
    if len(sys.argv) != 3:
        print(__doc__)
        return 1
    iface, cpu = sys.argv[1], int(sys.argv[2])
    found = irqs_for(iface)
    if not found:
        print("No IRQs found for interface %r" % iface)
        return 1
    for irq in found:
        try:
            with open('/proc/irq/%d/smp_affinity_list' % irq, 'w') as f:
                f.write(str(cpu))
            print("irq %d -> cpu %d" % (irq, cpu))
        except OSError as e:
            print("irq %d: %s (need root?)" % (irq, e))
    return 0


if __name__ == '__main__':
    sys.exit(main())
