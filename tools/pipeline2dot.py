#!/usr/bin/env python3
"""Reconstruct a running pipeline's block/ring graph from its ProcLogs
and emit graphviz DOT (reference: tools/pipeline2dot.py:97)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from bifrost_tpu import proclog  # noqa: E402


def get_data_flows(contents):
    """block -> ([in rings], [out rings]) from the in/out proclogs."""
    flows = {}
    for block, logs in contents.items():
        def rings(log):
            d = logs.get(log, {})
            return [d['ring%i' % i] for i in range(d.get('nring', 0))
                    if 'ring%i' % i in d]
        flows[block] = (rings('in'), rings('out'))
    return flows


def to_dot(contents):
    flows = get_data_flows(contents)
    lines = ['digraph pipeline {', '  rankdir=LR;']
    rings = set()
    for block, (ins, outs) in sorted(flows.items()):
        lines.append('  "%s" [shape=box,style=filled,'
                     'fillcolor=lightsteelblue];' % block)
        for r in ins:
            rings.add(r)
            lines.append('  "%s" -> "%s";' % (r, block))
        for r in outs:
            rings.add(r)
            lines.append('  "%s" -> "%s";' % (block, r))
    for r in sorted(rings):
        lines.append('  "%s" [shape=ellipse];' % r)
    lines.append('}')
    return '\n'.join(lines)


def main():
    if len(sys.argv) > 1:
        pid = int(sys.argv[1])
    else:
        base = proclog.proclog_dir()
        pids = sorted(int(p) for p in os.listdir(base) if p.isdigit()) \
            if os.path.isdir(base) else []
        if not pids:
            print("No running pipelines found", file=sys.stderr)
            return 1
        pid = pids[0]
    print(to_dot(proclog.load_by_pid(pid)))
    return 0


if __name__ == '__main__':
    sys.exit(main())
