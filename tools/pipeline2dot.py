#!/usr/bin/env python3
"""Reconstruct a running pipeline's block/ring graph from its ProcLogs
and emit graphviz DOT (reference: tools/pipeline2dot.py).

Annotations matching the reference's information set:
  * graph label with the pipeline's command line
  * block shapes by role (source=ellipse, sink=diamond, transform=box)
    and CPU binding ("CPU3" / "Unbound") in each block label
  * ring nodes annotated with space, size, and nringlet from the
    rings/<name> geometry ProcLogs
  * edge labels with the stream dtype where a sequence ProcLog
    records one
  * producer->ring edges labeled with occupancy % and gulps/s from the
    rings_flow/<name> ProcLogs the telemetry exporter publishes
    (docs/observability.md), so the graph doubles as a bottleneck map
    (a full ring ahead of a slow block shows up immediately); ring
    wait p99 is appended when the exporter recorded one
  * BridgeSink/BridgeSource rendered as CROSS-HOST boundary nodes
    (cds shape, gold fill, labeled with role + peer address) annotated
    with the live bridge tx/rx byte totals, rates, and reconnect
    counts from the ``<block>_bridge_transmit|capture/stats`` entries
    the transport publishes (docs/networking.md) — the inter-host hop
    is visible in the graph, not disguised as an ordinary block
  * dotted bidirectional association edges between blocks bound to the
    same core (reference: pipeline2dot.py:188-219)
  * compiled pipeline segments (bifrost_tpu.segments, docs/perf.md)
    rendered as ONE dashed cluster per segment: the member blocks
    grouped with the segment node, the elided interior rings dashed +
    grayed, the cluster labeled with the live dispatches-per-gulp
    from the segment's perf key — fusion is visible instead of
    looking like a chain of dead blocks
  * static-verifier diagnostics (bifrost_tpu.analysis.verify, published
    to the ``analysis/verify`` ProcLog by BF_VALIDATE=warn|strict)
    overlaid on the graph: rings/edges carrying a BF-E render red,
    BF-W amber, with the code + message as the node/edge tooltip — the
    bottleneck map doubles as a config-review map (docs/analysis.md)
"""

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from bifrost_tpu import proclog  # noqa: E402
from bifrost_tpu.monitor_utils import (get_best_size,  # noqa: E402
                                       get_command_line, ring_geometry)


def _is_ring_entry(block):
    return block.replace(os.sep, '/').startswith('rings')



def get_data_flows(contents):
    """block -> ([in rings], [out rings]); also classify sources/sinks
    (reference: pipeline2dot.py:97-136)."""
    flows, sources, sinks = {}, [], []
    for block, logs in contents.items():
        if _is_ring_entry(block):
            continue
        rins, routs = [], []
        found = False
        for log, dest in (('in', rins), ('out', routs)):
            d = logs.get(log, {})
            for key in sorted(d):
                if key.startswith('ring'):
                    found = True
                    if d[key] not in dest:
                        dest.append(d[key])
        flows[block] = (rins, routs)
        if found and not rins:
            sources.append(block)
        if found and not routs:
            sinks.append(block)
    return flows, sources, sinks


_DTYPE_RE = re.compile(r"'dtype':\s*'([^']+)'")


def stream_dtype(logs):
    """dtype recorded by a block's sequence ProcLogs, if any
    (reference reads nbit/complex from sequence logs,
    pipeline2dot.py:160-168)."""
    for name, d in logs.items():
        if not name.startswith('sequence'):
            continue
        if 'dtype' in d:
            return str(d['dtype'])
        tensor = d.get('_tensor')
        if isinstance(tensor, str):
            m = _DTYPE_RE.search(tensor)
            if m:
                return m.group(1)
    return None


def core_associations(contents):
    """Pairs of blocks bound to a common core
    (reference: pipeline2dot.py:188-219)."""
    cores = {}
    for block, logs in contents.items():
        if _is_ring_entry(block):
            continue
        bound = []
        i = 0
        while 'core%i' % i in logs.get('bind', {}):
            bound.append(logs['bind']['core%i' % i])
            i += 1
        if bound:
            cores[block] = set(bound)
    pairs = []
    names = sorted(cores)
    for i, a in enumerate(names):
        for b in names[i + 1:]:
            if cores[a] & cores[b] and cores[a] != {-1}:
                pairs.append((a, b))
    return pairs


#: suffixes of the transport's stats ProcLog directories — these are
#: per-endpoint telemetry attachments, not pipeline blocks
_BRIDGE_STAT_SUFFIXES = ('_bridge_transmit', '_bridge_capture')


def bridge_info(contents):
    """{block: {'role': 'sink'|'source', 'peer': 'addr:port'}} from
    the ``<block>/bridge`` ProcLogs the bridge blocks publish."""
    out = {}
    for block, logs in contents.items():
        if _is_ring_entry(block):
            continue
        b = logs.get('bridge')
        if isinstance(b, dict) and b.get('role'):
            out[block] = {'role': str(b['role']),
                          'peer': str(b.get('peer', '?'))}
    return out


def bridge_stats(contents, block):
    """The transport's live stats for a bridge block: tx or rx bytes,
    rate, and reconnect/dup counts from its ``*_bridge_transmit`` /
    ``*_bridge_capture`` stats entry (whichever exists)."""
    for suffix, kind in (('_bridge_transmit', 'tx'),
                         ('_bridge_capture', 'rx')):
        logs = contents.get(block + suffix)
        if not logs:
            continue
        stats = logs.get('stats', {})
        if not stats:
            continue
        nbytes = stats.get('nbytes', stats.get('ngood_bytes', 0))
        out = {'kind': kind, 'nbytes': int(float(nbytes or 0)),
               'rate_MBps': float(stats.get('rate_MBps', 0) or 0)}
        if kind == 'tx':
            out['reconnects'] = int(float(stats.get('reconnects', 0)
                                          or 0))
            out['nspans'] = int(float(stats.get('nspans', 0) or 0))
        else:
            out['dups'] = int(float(stats.get('nignored', 0) or 0))
        return out
    return None


def bridge_label(info, stats):
    """Boundary-node label lines under the block name."""
    parts = ['bridge %s <-> %s' % (info['role'], info['peer'])]
    if stats:
        sz, un = get_best_size(stats['nbytes'])
        line = '%s %.1f %s' % (stats['kind'], sz, un)
        if stats.get('rate_MBps'):
            line += ' @ %.1f MB/s' % stats['rate_MBps']
        parts.append(line)
        if stats.get('reconnects'):
            parts.append('%d reconnect(s)' % stats['reconnects'])
        if stats.get('dups'):
            parts.append('%d dup(s) dropped' % stats['dups'])
    return '\\n'.join(parts)


def segment_info(contents):
    """{segment block: {'members': [...], 'elided': [...], 'split':
    n, 'dpg': dispatches-per-gulp}} from the ``<block>/segment``
    ProcLogs compiled segments publish (bifrost_tpu.segments) plus
    the live ``segment_dispatches_per_gulp`` perf key.  pipeline2dot
    renders each as ONE cluster: the member blocks grouped with the
    segment node, the elided interior rings dashed — the graph shows
    the fusion instead of a chain of apparently-dead blocks."""
    out = {}
    for block, logs in contents.items():
        if _is_ring_entry(block):
            continue
        seg = logs.get('segment')
        if not isinstance(seg, dict) or 'members' not in seg:
            continue
        perf = logs.get('perf', {})
        try:
            dpg = float(perf.get('segment_dispatches_per_gulp', 0))
        except (TypeError, ValueError):
            dpg = 0.0
        out[block] = {
            'members': [m for m in
                        str(seg.get('members', '')).split(',') if m],
            'elided': [r for r in
                       str(seg.get('elided', '')).split(',') if r],
            'split': int(float(seg.get('split', 0) or 0)),
            'dpg': dpg,
        }
    return out


def ring_flow(contents):
    """rings_flow/<name> ProcLogs -> {ring_name: fields} (published by
    telemetry.exporter.MetricsPublisher)."""
    out = {}
    for block, logs in contents.items():
        norm = block.replace(os.sep, '/')
        if norm == 'rings_flow':
            out.update({k: dict(v) for k, v in logs.items()})
        elif norm.startswith('rings_flow/'):
            name = norm.split('/', 1)[1]
            for fields in logs.values():
                out[name] = dict(fields)
    return out


def flow_label(flow):
    """Edge-label text for one ring's flow entry ('' when idle)."""
    if not flow:
        return ''
    parts = []
    if 'occupancy_pct' in flow:
        parts.append('%.0f%% full' % float(flow['occupancy_pct']))
    if flow.get('gulps_per_s'):
        parts.append('%.1f gulps/s' % float(flow['gulps_per_s']))
    elif 'gulps' in flow:
        parts.append('%d gulps' % int(flow['gulps']))
    wait = flow.get('reserve_wait_p99_ms')
    if wait:
        parts.append('p99 wait %.1fms' % float(wait))
    return '\\n'.join(parts)


def verifier_diags(contents):
    """Diagnostics published to the ``analysis/verify`` ProcLog
    (bifrost_tpu.analysis.verify.publish_diagnostics): two maps,
    {block_name: [diag]} and {ring_name: [diag]}."""
    by_block, by_ring = {}, {}
    for block, logs in contents.items():
        if block.replace(os.sep, '/') != 'analysis':
            continue
        entry = logs.get('verify', {})
        diag_keys = (k for k in entry
                     if k.startswith('diag') and k[4:].isdigit())
        for key in sorted(diag_keys, key=lambda k: int(k[4:])):
            try:
                d = json.loads(str(entry[key]))
            except (ValueError, TypeError):
                continue
            if not isinstance(d, dict) or 'code' not in d:
                continue
            if d.get('block'):
                by_block.setdefault(str(d['block']), []).append(d)
            if d.get('ring'):
                by_ring.setdefault(str(d['ring']), []).append(d)
    return by_block, by_ring


#: severity -> (edge/border color, node fill) for the diagnostic
#: overlay; errors dominate warnings, info is not rendered
_DIAG_STYLE = {'error': ('red', 'lightsalmon'),
               'warning': ('orange2', 'navajowhite')}


def _diag_overlay(diags):
    """(color, fill, tooltip) for a node/edge carrying ``diags``, or
    None when only info-level findings are present."""
    worst = None
    for d in diags:
        sev = d.get('severity')
        if sev == 'error':
            worst = 'error'
            break
        if sev == 'warning':
            worst = 'warning'
    if worst is None:
        return None
    color, fill = _DIAG_STYLE[worst]
    tooltip = ' | '.join(
        '%s: %s' % (d.get('code'), d.get('message'))
        for d in diags if d.get('severity') != 'info')
    return color, fill, tooltip.replace('"', "'")


def to_dot(pid, contents, associations=True):
    flows, sources, sinks = get_data_flows(contents)
    geometry = ring_geometry(contents)
    ring_flows = ring_flow(contents)
    bridges = bridge_info(contents)
    segments = segment_info(contents)
    diag_blocks, diag_rings = verifier_diags(contents)
    cmd = get_command_line(pid)
    if cmd.startswith('python'):
        cmd = cmd.split(None, 1)[-1]
    cmd = os.path.basename(cmd.split(None, 1)[0]) if cmd else ''

    # compiled-segment membership: member blocks and elided interior
    # rings render INSIDE their segment's cluster (dashed border); a
    # block name may be stored with or without the pipeline prefix,
    # so membership matches on the trailing path component too
    seg_of_block, seg_of_ring = {}, {}
    for seg, info in segments.items():
        seg_of_block[seg] = seg
        for m in info['members']:
            seg_of_block[m] = seg
            seg_of_block[m.split('/')[-1]] = seg
        for r in info['elided']:
            seg_of_ring[r] = seg

    def _block_segment(block):
        return seg_of_block.get(block) or \
            seg_of_block.get(block.split('/')[-1])

    lines = ['digraph graph%d {' % pid,
             '  rankdir=LR;',
             '  labelloc="t";',
             '  label="Pipeline: %s\\n ";' % cmd]
    cluster_nodes = {seg: [] for seg in segments}

    def emit_node(line, block=None, ring=None):
        seg = _block_segment(block) if block is not None \
            else seg_of_ring.get(ring)
        if seg in cluster_nodes:
            cluster_nodes[seg].append(line)
        else:
            lines.append(line)

    rings = set()
    for block, (ins, outs) in sorted(flows.items()):
        # the transport's per-endpoint stats directories are telemetry
        # attachments of a bridge block, not pipeline blocks
        if block.endswith(_BRIDGE_STAT_SUFFIXES):
            continue
        logs = contents[block]
        core = logs.get('bind', {}).get('core0', None)
        cpu = 'Unbound' if core in (None, -1) else 'CPU%s' % core
        if block in bridges:
            # cross-host boundary node: the stream leaves/enters this
            # process here — annotate with the live transport figures
            info = bridges[block]
            stats = bridge_stats(contents, block)
            emit_node('  "%s" [label="%s\\n%s\\n%s" shape="cds" '
                      'style=filled fillcolor=lightgoldenrod];'
                      % (block, block, cpu,
                         bridge_label(info, stats)), block=block)
        else:
            shape = 'ellipse' if block in sources else \
                'diamond' if block in sinks else 'box'
            overlay = _diag_overlay(diag_blocks.get(block, ()))
            if overlay is not None:
                # verifier finding on this block: tinted fill + a
                # colored border, tooltip carries code + message
                color, fill, tip = overlay
                emit_node('  "%s" [label="%s\\n%s" shape="%s" '
                          'style=filled fillcolor=%s color=%s '
                          'penwidth=2 tooltip="%s"];'
                          % (block, block, cpu, shape, fill,
                             color, tip), block=block)
            else:
                emit_node('  "%s" [label="%s\\n%s" shape="%s" '
                          'style=filled fillcolor=lightsteelblue];'
                          % (block, block, cpu, shape), block=block)
        # sequence proclogs record the block's INPUT header
        # (pipeline.py MultiTransformBlock.main), so the dtype label
        # belongs on the input edges only
        dtype = stream_dtype(logs)

        def edge_attrs(r, label):
            attrs = []
            if label:
                attrs.append('label="%s"' % label)
            overlay = _diag_overlay(diag_rings.get(str(r), ()))
            if overlay is not None:
                color, _fill, tip = overlay
                attrs.append('color=%s penwidth=2 tooltip="%s"'
                             % (color, tip))
            return ' [%s]' % ' '.join(attrs) if attrs else ''

        for r in ins:
            rings.add(r)
            lines.append('  "ring:%s" -> "%s"%s;'
                         % (r, block, edge_attrs(r, dtype or '')))
        for r in outs:
            rings.add(r)
            fl = flow_label(ring_flows.get(str(r), {}))
            lines.append('  "%s" -> "ring:%s"%s;'
                         % (block, r, edge_attrs(r, fl)))
    for r in sorted(rings):
        dtl = geometry.get(str(r), {})
        if 'stride' in dtl:
            sz, un = get_best_size(
                float(dtl['stride']) *
                max(int(dtl.get('nringlet', 1)), 1))
            extra = '\\n%s  %.1f %s' % (dtl.get('space', '?'), sz, un)
            nringlet = int(dtl.get('nringlet', 1))
            if nringlet > 1:
                extra += '  x%d ringlets' % nringlet
        else:
            extra = ''
        if str(r) in seg_of_ring:
            # elided interior ring of a compiled segment: still shown
            # (the topology is real) but dashed + grayed — no span
            # ever flows through it while the segment is fused
            emit_node('  "ring:%s" [label="%s%s\\n(elided)" '
                      'shape=ellipse style=dashed color=gray50 '
                      'fontcolor=gray50];' % (r, r, extra),
                      ring=str(r))
        else:
            lines.append('  "ring:%s" [label="%s%s" shape=ellipse];'
                         % (r, r, extra))
    # compiled-segment clusters (bifrost_tpu.segments): one dashed box
    # around the segment node, its member blocks, and the elided
    # interior rings, labeled with the LIVE dispatch amortization from
    # the segment's perf proclog (docs/perf.md).  Graphviz assigns a
    # node to the FIRST (sub)graph that mentions it, and the edge
    # statements above already name the member/ring nodes at the root
    # — so the cluster subgraphs must be INSERTED before every edge,
    # right after the graph header, or they render as empty boxes
    cluster_lines = []
    for i, (seg, info) in enumerate(sorted(segments.items())):
        label = 'compiled segment (%d blocks' % len(info['members'])
        if info.get('split'):
            label += ', split %d' % info['split']
        label += ')'
        if info.get('dpg'):
            label += '\\n%.4g dispatches/gulp' % info['dpg']
        cluster_lines.append('  subgraph cluster_segment_%d {' % i)
        cluster_lines.append('    label="%s";' % label)
        cluster_lines.append('    style=dashed; color=steelblue; '
                             'fontcolor=steelblue;')
        for node in cluster_nodes.get(seg, []):
            cluster_lines.append('  ' + node)
        cluster_lines.append('  }')
    lines[4:4] = cluster_lines
    if associations:
        for a, b in core_associations(contents):
            lines.append('  "%s" -> "%s" [style="dotted" dir="both"];'
                         % (a, b))
    lines.append('}')
    return '\n'.join(lines)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('pid', nargs='?', type=int,
                    help='pipeline PID (default: first found)')
    ap.add_argument('-n', '--no-associations', action='store_true',
                    help='exclude same-core association edges')
    args = ap.parse_args()
    pid = args.pid
    if pid is None:
        base = proclog.proclog_dir()
        pids = sorted(int(p) for p in os.listdir(base)
                      if p.isdigit()) if os.path.isdir(base) else []
        if not pids:
            print('No running pipelines found', file=sys.stderr)
            return 1
        pid = pids[0]
    print(to_dot(pid, proclog.load_by_pid(pid),
                 associations=not args.no_associations))
    return 0


if __name__ == '__main__':
    sys.exit(main())
