#!/usr/bin/env python3
"""Chaos/soak gate: the overload-resilience layer must hold its
invariants under a scripted fault schedule.

Runs bench_suite config 15 (tools/chaos_soak.py machinery: a bridged
two-process pipeline driven through an overload burst, a connection
kill/redial, and a deterministic mid-stream block failure —
docs/robustness.md "Overload & degradation") in a fresh subprocess
pinned to the CPU backend, and asserts the soak's invariants:

- ``no_deadlock``            — both pipeline processes exited cleanly;
- ``no_silent_loss``         — produced == delivered + shed bytes,
  exact across BOTH shed ledgers (every missing gulp is a counted
  shed, never a silent gap);
- ``shedding_engaged``       — the burst actually forced counted
  shedding (a soak that never overloads proves nothing);
- ``health_traversal``       — pipeline health reached SHEDDING and
  returned to OK;
- ``p99_under_budget``       — capture-to-exit p99 stayed under
  ``BF_SLO_MS`` while shedding;
- ``recovered_reconnects`` / ``restart_recovered`` /
  ``overload_stamped`` — the kill redialed-and-resumed, the injected
  failure cost exactly one supervisor restart, and downstream
  sequence headers carry the ``_overload`` shed stamp.

The full config result is written to the ``--out`` JSON artifact
(``CHAOS_SOAK_${ROUND}.json``) so bench rounds record the overload
path's health next to the throughput numbers.

Exit codes: 0 pass, 3 an invariant failed, 2 the soak failed to run.
``tools/watch_and_bench.sh`` runs this after the bridge gate
(``BF_SKIP_CHAOS_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_config15(timeout=900):
    """One bench_suite --config 15 subprocess on the CPU backend;
    returns its result dict."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    # configured overload/fault tuning would skew the scripted drill
    for var in ('BF_OVERLOAD_POLICY', 'BF_FAULTS', 'BF_SLO_MS',
                'BF_BRIDGE_WINDOW', 'BF_BRIDGE_STREAMS',
                'BF_BRIDGE_QUOTA_MBPS', 'BF_BRIDGE_QUOTA_GULPS'):
        env.pop(var, None)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
         '--config', '15'],
        capture_output=True, text=True, env=env, cwd=ROOT,
        timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and 'invariants' in d:
            return d
    raise RuntimeError(
        'config 15 produced no invariants result (rc=%d):\n%s\n%s'
        % (out.returncode, out.stdout[-1200:], out.stderr[-1200:]))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default='CHAOS_SOAK.json',
                    help='artifact path for the full config result')
    ap.add_argument('--timeout', type=int, default=900)
    args = ap.parse_args(argv)
    try:
        res = run_config15(timeout=args.timeout)
    except Exception as exc:
        print('chaos_gate: soak failed to run: %s: %s'
              % (type(exc).__name__, exc))
        return 2
    with open(args.out, 'w') as f:
        json.dump(res, f, indent=2, sort_keys=True)
    inv = res.get('invariants', {})
    for name in sorted(inv):
        print('%-22s %s' % (name, 'ok' if inv[name] else 'FAIL'))
    print('ledger: %s' % json.dumps(res.get('ledger', {}),
                                    sort_keys=True))
    ok = bool(inv) and all(inv.values())
    print('chaos_gate: %s -> %s' % ('PASS' if ok else 'FAIL',
                                    args.out))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
