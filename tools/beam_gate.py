#!/usr/bin/env python3
"""Quantized-beamformer gate: the measured quantized winner must beat
the f32 baseline on the end-to-end chain, within its accuracy class.

Runs bench_suite config 13 (ci8 capture -> H2D -> beamform -> Stokes
detect -> integrate -> sink; min-of-N with alternating arms —
bench_suite.bench_beamform_chain) in a fresh subprocess pinned to the
CPU backend, and asserts:

- ``quant_beats_f32`` — the quantized arm's min-of-N wall time beats
  the f32 XLA-baseline arm's (speedup >= ``--min-speedup``; measured
  selection must find a winner on this host or the whole quantized
  engine is a no-op here);
- ``within_class``    — the quantized arm's output stays inside the
  declared 'int8' accuracy-class bound (BEAM_CLASSES['int8'] rtol) of
  the f32 arm — a lossy winner can never buy speed with unbounded
  error;
- ``deterministic``   — quant-arm outputs are byte-identical across
  repetitions (same winner, same program, same stream).

The ops/s-per-chip number the artifact carries is the row docs/perf.md
publishes next to the spectrometer.  The arm interleaving / min-of-N
noise defenses live inside config 13 itself (the config-9 policy).

Exit codes: 0 pass, 3 a gate condition failed, 2 the bench arm failed
to produce a result.  ``tools/watch_and_bench.sh`` runs this after the
batch gate (``BF_SKIP_BEAM_GATE=1`` opts out).
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_config13(timeout=1800):
    """One bench_suite --config 13 subprocess on the CPU backend with
    a private probe-cache dir (a stale winner frozen by an earlier
    session must not skew the race); returns its result dict."""
    with tempfile.TemporaryDirectory(prefix='beam_gate_') as cache:
        env = dict(os.environ, JAX_PLATFORMS='cpu',
                   BF_CACHE_DIR=cache)
        env.pop('BF_BEAM_IMPL', None)        # a forced impl skews arms
        env.pop('BF_BEAM_GATE_RTOL', None)
        out = subprocess.run(
            [sys.executable, os.path.join(ROOT, 'bench_suite.py'),
             '--config', '13'],
            capture_output=True, text=True, env=env, cwd=ROOT,
            timeout=timeout)
    for line in out.stdout.splitlines():
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and 'arms' in d:
            return d
    raise RuntimeError(
        'config 13 produced no arms result (rc=%d):\n%s\n%s'
        % (out.returncode, out.stdout[-1000:], out.stderr[-1000:]))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--out', default='BENCH_BEAM.json',
                    help='artifact path (full config-13 result + '
                         'verdict)')
    ap.add_argument('--min-speedup', type=float, default=1.0,
                    help='required quantized-vs-f32 chain speedup '
                         '(min-of-N)')
    ap.add_argument('--timeout', type=float, default=1800.0,
                    help='bench subprocess timeout in seconds')
    args = ap.parse_args()

    try:
        res = run_config13(timeout=args.timeout)
    except (RuntimeError, subprocess.TimeoutExpired) as exc:
        print('beam_gate: bench arm failed: %s' % exc,
              file=sys.stderr)
        return 2

    speedup = float(res.get('value') or 0.0)
    speed_ok = bool(res.get('quant_beats_f32')) and \
        speedup >= args.min_speedup
    class_ok = bool(res.get('within_class'))
    det_ok = bool(res.get('deterministic'))
    ok = speed_ok and class_ok and det_ok
    artifact = dict(res,
                    gate={'speedup': speedup,
                          'min_speedup': args.min_speedup,
                          'speed_ok': speed_ok,
                          'within_class': class_ok,
                          'deterministic': det_ok,
                          'pass': ok,
                          'round': os.environ.get('BF_BENCH_ROUND',
                                                  '')})
    with open(args.out, 'w') as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write('\n')
    print('beam_gate: f32 %.1fms / quant %.1fms (winner %s) -> '
          '%.2fx (need >= %.2fx), rel_err %.2e (class rtol %g), '
          'deterministic=%s, %.1f Gop/s/chip %s'
          % (res['arms']['f32']['ms_min'],
             res['arms']['quant']['ms_min'],
             res['arms']['quant'].get('winner'),
             speedup, args.min_speedup,
             res.get('beam_rel_err', float('nan')),
             res.get('class_rtol', float('nan')),
             det_ok, res.get('gops_per_s_per_chip', 0.0),
             'PASS' if ok else 'FAIL'))
    return 0 if ok else 3


if __name__ == '__main__':
    sys.exit(main())
