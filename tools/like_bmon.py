#!/usr/bin/env python3
"""bmon-style monitor of packet capture/transmit statistics
(reference: tools/like_bmon.py).  Reads the capture engines'
ProcLog stats entries."""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from bifrost_tpu import proclog  # noqa: E402


def main():
    once = '--once' in sys.argv
    base = proclog.proclog_dir()
    while True:
        rows = []
        if os.path.isdir(base):
            for pid_s in sorted(os.listdir(base)):
                if not pid_s.isdigit():
                    continue
                contents = proclog.load_by_pid(int(pid_s))
                for block, logs in sorted(contents.items()):
                    st = logs.get('stats', {})
                    if 'ngood_bytes' in st:
                        rows.append((pid_s, block,
                                     st.get('ngood_bytes', 0),
                                     st.get('nmissing_bytes', 0),
                                     st.get('ninvalid', 0)))
        if not once:
            os.system('clear')
        print('%-8s %-32s %14s %14s %8s'
              % ('PID', 'CAPTURE', 'GOOD_BYTES', 'MISSING', 'INVALID'))
        for r in rows:
            print('%-8s %-32s %14s %14s %8s' % r)
        if once:
            return 0
        time.sleep(1.0)


if __name__ == '__main__':
    sys.exit(main())
