#!/usr/bin/env python3
"""bmon-style monitor of packet capture/transmit statistics
(reference: tools/like_bmon.py).

Information set matching the reference:
  * per-PID summary: RX rate (B/s), RX packets/s, TX rate, TX pkt/s
  * per-block detail for the selected PID: good/missing/invalid/ignored
    byte totals, global and current loss percentages (gloss/closs)
  * rolling rate history rendered as an ASCII bar graph per direction

Rates come from deltas of successive ProcLog samples of the capture
engines' ``*_capture/stats`` entries (ngood_bytes/nmissing_bytes/
ninvalid/nignored/npackets) and the writers' ``*_transmit_*/stats``
(nbytes/npackets).  Ring-bridge endpoints (io/bridge.py) publish the
same stats shapes under ``*_bridge_transmit`` / ``*_bridge_capture``
and show up as rows tagged ``[bridge]`` — for a bridge, ``invalid``
counts CRC failures and ``ignored`` counts duplicate frames dropped
after a reconnect.  Curses UI: up/down select PID, q quits; ``--once``
prints a plain-text snapshot of every PID.
"""

import argparse
import os
import socket
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from bifrost_tpu import proclog  # noqa: E402
from bifrost_tpu.monitor_utils import list_pipelines  # noqa: E402

_HISTORY = 60


def get_transmit_receive():
    """Snapshot all capture (RX) and transmit (TX) stats blocks across
    pipelines (reference: like_bmon.py:51-88)."""
    now = time.time()
    found = {}
    for pid in list_pipelines():
        contents = proclog.load_by_pid(pid)
        for block, logs in contents.items():
            st = logs.get('stats')
            if not st:
                continue
            if 'ngood_bytes' in st:
                kind = 'rx'
                entry = {'good': st.get('ngood_bytes', 0),
                         'missing': st.get('nmissing_bytes', 0),
                         'invalid': st.get('ninvalid', 0),
                         'ignored': st.get('nignored', 0),
                         'npackets': st.get('npackets', 0)}
                # sharded capture engines publish per-worker counters
                # (workerN_npackets/_nbytes/_zero_copy)
                workers, i = [], 0
                while ('worker%d_npackets' % i) in st:
                    workers.append({
                        'npackets': st['worker%d_npackets' % i],
                        'nbytes': st.get('worker%d_nbytes' % i, 0),
                        'zero_copy':
                            st.get('worker%d_zero_copy' % i, 0)})
                    i += 1
                if workers:
                    entry['workers'] = workers
            elif 'nbytes' in st:
                kind = 'tx'
                entry = {'good': st.get('nbytes', 0), 'missing': 0,
                         'invalid': 0, 'ignored': 0,
                         'npackets': st.get('npackets', 0)}
            else:
                continue
            entry.update({'pid': pid, 'name': block, 'kind': kind,
                          'time': now,
                          'bridge': '_bridge_' in block})
            found['%s-%s' % (pid, block)] = entry
    return found


def get_statistics(curr_list, prev_list):
    """Per-PID aggregated rates and loss percentages from two snapshots
    (reference: like_bmon.py:108-188)."""
    out = {}
    for key, curr in curr_list.items():
        pid, kind = curr['pid'], curr['kind']
        prev = prev_list.get(key)
        drate = prate = 0.0
        if prev is not None and curr['time'] > prev['time']:
            dt = curr['time'] - prev['time']
            drate = (curr['good'] - prev['good']) / dt
            prate = (curr['npackets'] - prev['npackets']) / dt
        gloss = closs = 0.0
        denom = curr['good'] + curr['missing']
        if denom > 0:
            gloss = 100.0 * curr['missing'] / denom
        if prev is not None:
            dmiss = curr['missing'] - prev['missing']
            dgood = curr['good'] - prev['good']
            if dmiss + dgood > 0:
                closs = 100.0 * dmiss / (dmiss + dgood)
        if pid not in out:
            out[pid] = {d: {'good': 0, 'missing': 0, 'invalid': 0,
                            'ignored': 0, 'drate': 0.0, 'prate': 0.0,
                            'gloss': 0.0, 'closs': 0.0, 'blocks': []}
                        for d in ('rx', 'tx')}
        agg = out[pid][kind]
        for k in ('good', 'missing', 'invalid', 'ignored'):
            agg[k] += curr[k]
        agg['drate'] += max(0.0, drate)
        agg['prate'] += max(0.0, prate)
        agg['gloss'] = max(agg['gloss'], gloss)
        agg['closs'] = max(agg['closs'], closs)
        workers = []
        for i, w in enumerate(curr.get('workers', [])):
            wprev = (prev or {}).get('workers', [])
            wrate = 0.0
            if i < len(wprev) and prev is not None and \
                    curr['time'] > prev['time']:
                wrate = (w['npackets'] - wprev[i]['npackets']) / \
                    (curr['time'] - prev['time'])
            workers.append(dict(w, prate=max(0.0, wrate)))
        agg['blocks'].append({
            'name': curr['name'], 'good': curr['good'],
            'missing': curr['missing'], 'invalid': curr['invalid'],
            'ignored': curr['ignored'], 'drate': max(0.0, drate),
            'prate': max(0.0, prate), 'gloss': gloss, 'closs': closs,
            'bridge': curr.get('bridge', False), 'workers': workers})
    return out


def set_units(value):
    """Human units for a B/s rate (reference: like_bmon.py:190-207)."""
    for mag, unit in ((1024.0 ** 3, 'GB/s'), (1024.0 ** 2, 'MB/s'),
                      (1024.0, 'kB/s')):
        if value >= mag:
            return value / mag, unit
    return value, 'B/s'


def bar_graph(history, width=60, height=4):
    """ASCII bar graph of a rate history (the reference's graphical
    pane analogue)."""
    hist = list(history)[-width:]
    peak = max(hist) if hist and max(hist) > 0 else 1.0
    rows = []
    for level in range(height, 0, -1):
        thresh = peak * (level - 0.5) / height
        rows.append(''.join('#' if v >= thresh else ' ' for v in hist))
    pv, pu = set_units(peak)
    rows[0] += '  peak %.1f %s' % (pv, pu)
    return rows


def render_pid(pid, stats, history, width=78):
    """Detail pane for one PID: totals + per-block table + history
    graphs."""
    out = []
    st = stats.get(pid)
    if st is None:
        return ['(no capture/transmit stats for pid %s)' % pid]
    for kind, label in (('rx', 'RX'), ('tx', 'TX')):
        agg = st[kind]
        if not agg['blocks']:
            continue
        dv, du = set_units(agg['drate'])
        out.append('%s: %8.2f %-5s %8.1f pkt/s   loss %5.1f%% now, '
                   '%5.1f%% total'
                   % (label, dv, du, agg['prate'], agg['closs'],
                      agg['gloss']))
        out.append('  %-28s %12s %12s %9s %9s %7s'
                   % ('block', 'good_bytes', 'missing', 'invalid',
                      'ignored', 'rate'))
        for b in sorted(agg['blocks'], key=lambda b: b['name']):
            bv, bu = set_units(b['drate'])
            tag = ' [bridge]' if b.get('bridge') else ''
            out.append('  %-28s %12d %12d %9d %9d %5.1f%s%s'
                       % (b['name'][:28], b['good'], b['missing'],
                          b['invalid'], b['ignored'], bv, bu[0], tag))
            for i, w in enumerate(b.get('workers', [])):
                zc_pct = 100.0 * w['zero_copy'] / w['npackets'] \
                    if w['npackets'] else 0.0
                wv, wu = set_units(w['nbytes'])
                out.append('    worker%-2d %10d pkts %8.1f %-4s '
                           '%8.1f pkt/s  zero-copy %5.1f%%'
                           % (i, w['npackets'], wv, wu.rstrip('/s'),
                              w['prate'], zc_pct))
        hist = history.get((pid, kind))
        if hist:
            out.append('  history (%ds):' % len(hist))
            out.extend('  ' + r for r in bar_graph(hist, width - 4))
    return out


def render_summary(stats):
    out = ['%7s  %11s %10s  %11s %10s'
           % ('PID', 'RX Rate', 'RX pkt/s', 'TX Rate', 'TX pkt/s')]
    for pid in sorted(stats, key=str):
        rx, tx = stats[pid]['rx'], stats[pid]['tx']
        rv, ru = set_units(rx['drate'])
        tv, tu = set_units(tx['drate'])
        out.append('%7s  %6.1f %-4s %10.1f  %6.1f %-4s %10.1f'
                   % (pid, rv, ru, rx['prate'], tv, tu, tx['prate']))
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--once', action='store_true',
                    help='print one plain-text snapshot and exit')
    ap.add_argument('--interval', type=float, default=1.0)
    args = ap.parse_args()

    host = socket.gethostname()
    prev = get_transmit_receive()
    history = {}

    def poll():
        nonlocal prev
        time.sleep(0.2 if args.once else 0)
        curr = get_transmit_receive()
        stats = get_statistics(curr, prev)
        prev = curr
        for pid, st in stats.items():
            for kind in ('rx', 'tx'):
                history.setdefault((pid, kind), []).append(
                    st[kind]['drate'])
                del history[(pid, kind)][:-_HISTORY]
        return stats

    if args.once:
        stats = poll()
        print('like_bmon - %s' % host)
        for line in render_summary(stats):
            print(line)
        for pid in sorted(stats, key=str):
            print()
            print('PID %s:' % pid)
            for line in render_pid(pid, stats, history):
                print(line)
        return 0

    import curses

    def loop(scr):
        curses.use_default_colors()
        scr.nodelay(1)
        sel, t_last, stats = 0, 0.0, {}
        while True:
            ch = scr.getch()
            curses.flushinp()
            if ch == ord('q'):
                break
            if ch == curses.KEY_UP:
                sel -= 1
            elif ch == curses.KEY_DOWN:
                sel += 1
            if time.time() - t_last > args.interval:
                stats = poll()
                t_last = time.time()
            pids = sorted(stats, key=str)
            sel = min(max(sel, 0), max(len(pids) - 1, 0))
            maxy, maxx = scr.getmaxyx()
            lines = ['like_bmon - %s   (up/down: select pid, q: quit)'
                     % host, '']
            lines += render_summary(stats)
            lines.append('')
            if pids:
                lines.append('--- PID %s ---' % pids[sel])
                lines += render_pid(pids[sel], stats, history,
                                    width=maxx)
            for y, line in enumerate(lines[:maxy - 1]):
                try:
                    scr.addstr(y, 0, line[:maxx - 1])
                    scr.clrtoeol()
                except curses.error:
                    break
            scr.clrtobot()
            scr.refresh()
            time.sleep(0.2)

    curses.wrapper(loop)
    return 0


if __name__ == '__main__':
    sys.exit(main())
