// In-library self-test (reference analogue: src/testsuite.cpp:30-204,
// exposed through the ABI as bfTestSuite).  Exercises the ring core's
// contracts from C++ with no Python in the loop: geometry, sequence
// lifecycle, reserve/commit ordering, the partial-commit legality
// rules, ghost-region contiguity, and a reader round trip.
// Returns 0 on success, or a small failure code identifying the check.

#include <cstring>
#include <string>

extern "C" {
int bft_ring_create(void**, const char*);
int bft_ring_destroy(void*);
int bft_ring_resize(void*, long long, long long, long long);
int bft_ring_geometry(void*, unsigned char**, long long*, long long*,
                      long long*);
int bft_ring_begin_writing(void*);
int bft_ring_end_writing(void*);
int bft_ring_begin_sequence(void*, const char*, long long, const char*,
                            long long, long long, void**);
int bft_ring_end_sequence(void*, void*);
int bft_ring_reserve(void*, long long, int, long long*, long long*);
int bft_ring_commit(void*, long long, long long);
int bft_reader_create(void*, int, long long*);
int bft_reader_destroy(void*, long long);
int bft_ring_open_sequence(void*, int, const char*, long long, void**);
int bft_reader_acquire(void*, long long, void*, long long, long long,
                       long long, long long*, long long*);
int bft_reader_release(void*, long long, long long);

int bft_selftest(void) {
    void* ring = nullptr;
    if (bft_ring_create(&ring, "selftest") != 0) return 1;
    struct Cleanup {
        void* r;
        ~Cleanup() { bft_ring_destroy(r); }
    } cleanup{ring};

    if (bft_ring_resize(ring, 64, 256, 1) != 0) return 2;
    unsigned char* buf = nullptr;
    long long size = 0, ghost = 0, nrl = 0;
    if (bft_ring_geometry(ring, &buf, &size, &ghost, &nrl) != 0 ||
        !buf || size < 256 || ghost < 64 || nrl != 1)
        return 3;

    if (bft_ring_begin_writing(ring) != 0) return 4;
    void* seq = nullptr;
    const char* hdr = "{\"t\":1}";
    if (bft_ring_begin_sequence(ring, "s0", 42, hdr,
                                (long long)std::strlen(hdr), 1,
                                &seq) != 0)
        return 5;

    // reserve/commit with data, crossing the nominal end to exercise
    // the ghost mirror
    for (int k = 0; k < 6; ++k) {
        long long begin = 0, span_id = 0;
        if (bft_ring_reserve(ring, 48, 0, &begin, &span_id) != 0)
            return 6;
        bft_ring_geometry(ring, &buf, &size, &ghost, &nrl);
        std::memset(buf + (begin % size), 0x40 + k, 48);
        if (bft_ring_commit(ring, span_id, 48) != 0) return 7;
    }

    // partial-commit legality: with two outstanding spans, a partial
    // commit of the OLDER one must be rejected without corrupting state
    long long b1 = 0, id1 = 0, b2 = 0, id2 = 0;
    if (bft_ring_reserve(ring, 32, 0, &b1, &id1) != 0) return 8;
    if (bft_ring_reserve(ring, 32, 0, &b2, &id2) != 0) return 9;
    if (bft_ring_commit(ring, id1, 16) == 0) return 10;   // must fail
    if (bft_ring_commit(ring, id1, 32) != 0) return 11;   // recovers
    if (bft_ring_commit(ring, id2, 32) != 0) return 12;

    if (bft_ring_end_sequence(ring, seq) != 0) return 13;
    bft_ring_end_writing(ring);

    // reader round trip over the final spans
    long long reader = 0;
    if (bft_reader_create(ring, 1, &reader) != 0) return 14;
    void* rseq = nullptr;
    if (bft_ring_open_sequence(ring, 3 /* earliest */, "", -1,
                               &rseq) != 0)
        return 15;
    long long got_begin = 0, got_nbyte = 0;
    // the ring holds the last 256 bytes; ask for the final 48-byte gulp
    if (bft_reader_acquire(ring, reader, rseq, 5 * 48 + 64 - 48, 48, 48,
                           &got_begin, &got_nbyte) != 0)
        return 16;
    if (got_nbyte <= 0) return 17;
    bft_reader_release(ring, reader, got_begin);
    bft_reader_destroy(ring, reader);
    return 0;
}
}
