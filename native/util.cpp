// Host-side utility C ABI for bifrost_tpu: affinity, aligned memory,
// strided copies, and a native ProcLog writer.
//
// These are the reference's host-native utility surfaces re-expressed
// for the TPU runtime (reference: src/bifrost/affinity.h, memory.h,
// proclog.h; implementations src/affinity.cpp, src/memory.cpp,
// src/proclog.cpp).  Device ('tpu') memory is owned by XLA and never
// routes here — only the host side of the space lattice does, which is
// exactly the part the reference implements with plain
// posix_memalign/memcpy under its space dispatch.

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>

#if defined(__linux__)
#include <pthread.h>
#include <sched.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>
#endif

#define BFT_OK 0
#define BFT_ERR_INVALID (-1)
#define BFT_ERR_STATE (-2)
#define BFT_ERR_ALLOC (-3)
#define BFT_ERR_OS (-6)

namespace {
constexpr int64_t ALIGNMENT = 512;   // BF_ALIGNMENT-equivalent
}

extern "C" {

// ---- affinity (reference: src/affinity.cpp bfAffinitySetCore /
// bfAffinityGetCore) --------------------------------------------------

int bft_affinity_set_core(int core) {
#if defined(__linux__)
    cpu_set_t s;
    CPU_ZERO(&s);
    if (core >= 0) {
        if (core >= CPU_SETSIZE) return BFT_ERR_INVALID;
        CPU_SET(core, &s);
    } else {
        // core < 0: unbind (allow all online cpus)
        long n = sysconf(_SC_NPROCESSORS_ONLN);
        for (long c = 0; c < n && c < CPU_SETSIZE; ++c) CPU_SET(c, &s);
    }
    if (pthread_setaffinity_np(pthread_self(), sizeof(s), &s))
        return BFT_ERR_OS;
    return BFT_OK;
#else
    (void)core;
    return BFT_ERR_STATE;
#endif
}

int bft_affinity_get_core(int* core_out) {
#if defined(__linux__)
    if (!core_out) return BFT_ERR_INVALID;
    cpu_set_t s;
    CPU_ZERO(&s);
    if (pthread_getaffinity_np(pthread_self(), sizeof(s), &s))
        return BFT_ERR_OS;
    int found = -1, count = 0;
    for (int c = 0; c < CPU_SETSIZE; ++c) {
        if (CPU_ISSET(c, &s)) {
            if (!count) found = c;
            ++count;
        }
    }
    // single-core binding reports the core; multi-core reports -1,
    // matching the reference's semantics
    *core_out = (count == 1) ? found : -1;
    return BFT_OK;
#else
    if (core_out) *core_out = -1;
    return BFT_ERR_STATE;
#endif
}

// ---- aligned host memory (reference: src/memory.cpp bfMalloc/bfFree/
// bfMemcpy/bfMemcpy2D/bfMemset, host-space arms) ----------------------

int bft_malloc(void** ptr_out, int64_t size) {
    if (!ptr_out || size < 0) return BFT_ERR_INVALID;
    if (size == 0) {
        *ptr_out = nullptr;
        return BFT_OK;
    }
    void* p = nullptr;
    int64_t padded = ((size + ALIGNMENT - 1) / ALIGNMENT) * ALIGNMENT;
    if (posix_memalign(&p, ALIGNMENT, padded)) return BFT_ERR_ALLOC;
    *ptr_out = p;
    return BFT_OK;
}

int bft_free(void* ptr) {
    std::free(ptr);
    return BFT_OK;
}

int bft_memcpy(void* dst, const void* src, int64_t n) {
    if ((!dst || !src) && n) return BFT_ERR_INVALID;
    if (n < 0) return BFT_ERR_INVALID;
    std::memcpy(dst, src, (size_t)n);
    return BFT_OK;
}

int bft_memcpy2d(void* dst, int64_t dst_stride,
                 const void* src, int64_t src_stride,
                 int64_t width, int64_t height) {
    if (width < 0 || height < 0) return BFT_ERR_INVALID;
    if ((!dst || !src) && width && height) return BFT_ERR_INVALID;
    if (dst_stride < width || src_stride < width) return BFT_ERR_INVALID;
    auto* d = static_cast<char*>(dst);
    auto* s = static_cast<const char*>(src);
    for (int64_t r = 0; r < height; ++r)
        std::memcpy(d + r * dst_stride, s + r * src_stride,
                    (size_t)width);
    return BFT_OK;
}

int bft_memset(void* ptr, int value, int64_t n) {
    if (!ptr && n) return BFT_ERR_INVALID;
    if (n < 0) return BFT_ERR_INVALID;
    std::memset(ptr, value, (size_t)n);
    return BFT_OK;
}

int bft_memset2d(void* ptr, int64_t stride, int value,
                 int64_t width, int64_t height) {
    if (width < 0 || height < 0 || stride < width) return BFT_ERR_INVALID;
    if (!ptr && width && height) return BFT_ERR_INVALID;
    auto* d = static_cast<char*>(ptr);
    for (int64_t r = 0; r < height; ++r)
        std::memset(d + r * stride, value, (size_t)width);
    return BFT_OK;
}

// ---- ProcLog writer (reference: src/proclog.cpp ProcLog::update;
// layout <base>/<pid>/<block>/<log>, one "key : value" per line).
// The directory base matches bifrost_tpu/proclog.py so native blocks
// and Python blocks land in one tree. -----------------------------------

static std::string g_proclog_base;
static std::mutex g_proclog_mutex;

int bft_proclog_set_base(const char* base) {
    if (!base || !*base) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(g_proclog_mutex);
    g_proclog_base = base;
    return BFT_OK;
}

int bft_proclog_update(const char* block, const char* log,
                       const char* contents) {
#if defined(__linux__)
    if (!block || !log || !contents) return BFT_ERR_INVALID;
    std::string base;
    {
        std::lock_guard<std::mutex> lk(g_proclog_mutex);
        base = g_proclog_base;
    }
    if (base.empty()) return BFT_ERR_STATE;
    std::string dir = base + "/" +
        std::to_string((long long)getpid());
    if (mkdir(dir.c_str(), 0775) && errno != EEXIST) return BFT_ERR_OS;
    dir += "/";
    dir += block;
    if (mkdir(dir.c_str(), 0775) && errno != EEXIST) return BFT_ERR_OS;
    std::string tmp = dir + "/." + log + ".tmp";
    std::string fin = dir + "/" + log;
    FILE* f = std::fopen(tmp.c_str(), "w");
    if (!f) return BFT_ERR_OS;
    size_t len = std::strlen(contents);
    if (len && std::fwrite(contents, 1, len, f) != len) {
        std::fclose(f);
        std::remove(tmp.c_str());
        return BFT_ERR_OS;
    }
    std::fclose(f);
    if (std::rename(tmp.c_str(), fin.c_str())) {
        std::remove(tmp.c_str());
        return BFT_ERR_OS;
    }
    return BFT_OK;
#else
    (void)block; (void)log; (void)contents;
    return BFT_ERR_STATE;
#endif
}

}  // extern "C"
