// Native UDP capture engine for bifrost_tpu.
//
// The reference's packet capture is a C++ engine: a capture loop
// receives batches of datagrams, decodes per-telescope headers, and
// scatters payloads into a sliding window of two open ring spans with
// per-source loss accounting and >50%-loss blanking
// (reference: src/packet_capture.hpp:150-607 and the recvmmsg shim
// src/Socket.hpp:145-158).  This file is the TPU build's equivalent:
// it drives the native ring through the same BFT C ABI Python uses
// (native/ring.cpp) and calls back into Python only once per sequence
// for header construction (the C->Python callback boundary the
// reference also has, packet_capture.hpp:535-540).
//
// Formats: all 12 wire formats decode natively here, mirroring the
// Python codecs in bifrost_tpu/io/packet_formats.py (themselves
// mirrors of the reference decoders, src/formats/*.hpp); the transmit
// engine below fills all 12 headers (packet_writer.hpp:366-580).
// Engine equivalence is pinned by tests/test_udp_io.py, which runs
// every format through both engines.

#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

// The engine is Linux-only (recvmmsg/poll); elsewhere the ABI stubs
// return BFT_ERR_INVALID and Python auto-falls-back to its engine,
// keeping the native RING portable.
#if defined(__linux__)
#include <ctime>
#include <poll.h>
#include <sys/socket.h>
#include <sys/types.h>
#define BFT_HAVE_CAPTURE 1
#endif

#define BFT_OK 0
#define BFT_ERR_INVALID (-1)
#define BFT_ERR_STATE (-2)

// capture status codes (match bifrost_tpu.io.packet_capture)
#define CAPTURE_STARTED 1
#define CAPTURE_CONTINUED 2
#define CAPTURE_ENDED 4
#define CAPTURE_NO_DATA 8
#define CAPTURE_INTERRUPTED 16

extern "C" {
// ring ABI (native/ring.cpp)
int bft_ring_resize(void*, long long, long long, long long);
int bft_ring_geometry(void*, unsigned char**, long long*, long long*,
                      long long*);
int bft_ring_begin_writing(void*);
int bft_ring_end_writing(void*);
int bft_ring_begin_sequence(void*, const char*, long long, const char*,
                            long long, long long, void**);
int bft_ring_end_sequence(void*, void*);
int bft_ring_reserve(void*, long long, int, long long*, long long*);
int bft_ring_commit(void*, long long, long long);

typedef struct {
    long long seq;
    long long time_tag;
    int src;
    int nsrc;
    int nchan;
    int chan0;
    int tuning;
    int tuning1;
    int gain;
    int decimation;
    int beam;       // nbeam for pbeam/ibeam sequence headers
    int npol;       // snap2 / vbeam
    int npol_tot;   // snap2
    int pol0;       // snap2
    int nchan_tot;  // snap2
    int payload_size;
} bft_pkt_desc;

// Python fills time_tag_out, the sequence name, and a JSON header
// (NUL-terminated, <= caps); returns 0 on success.
typedef int (*bft_header_cb)(void* user, const bft_pkt_desc* desc,
                             long long* time_tag_out, char* name_buf,
                             int name_cap, char* hdr_json, int hdr_cap);
}

#if BFT_HAVE_CAPTURE
namespace {

enum Format { FMT_SIMPLE = 0, FMT_CHIPS = 1, FMT_TBN = 2,
              FMT_DRX = 3, FMT_DRX8 = 4, FMT_IBEAM = 5, FMT_COR = 6,
              FMT_PBEAM = 7, FMT_SNAP2 = 8, FMT_VDIF = 9,
              FMT_TBF = 10, FMT_VBEAM = 11 };

// pbeam/cor compose src from multiple wire fields, and the reference
// applies src0 in beam/baseline units INSIDE the decoder
// (pbeam.hpp:70, cor.hpp:77); for those the engine's flat rebase is
// skipped (matching bifrost_tpu.io.packet_capture._PacketCapture).
static inline bool src0_in_decoder(int fmt) {
    return fmt == FMT_PBEAM || fmt == FMT_COR;
}

// Decode one datagram; mirrors the Python codecs in
// bifrost_tpu/io/packet_formats.py (themselves mirrors of the
// reference decoders).  Returns false for runts/invalid packets.
static inline uint64_t be64(const uint8_t* p) {
    uint64_t v = 0;
    for (int i = 0; i < 8; ++i) v = (v << 8) | p[i];
    return v;
}
static inline uint16_t be16(const uint8_t* p) {
    return (uint16_t)((p[0] << 8) | p[1]);
}
static inline void wbe64(uint8_t* p, uint64_t v) {
    for (int i = 7; i >= 0; --i) { p[i] = (uint8_t)v; v >>= 8; }
}
static inline void wbe16(uint8_t* p, uint16_t v) {
    p[1] = (uint8_t)v;
    p[0] = (uint8_t)(v >> 8);
}

static inline uint32_t le32(const uint8_t* p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
           ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
}
static inline uint64_t le64(const uint8_t* p) {
    return (uint64_t)le32(p) | ((uint64_t)le32(p + 4) << 32);
}
static inline uint32_t be32(const uint8_t* p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}
static inline void wbe32(uint8_t* p, uint32_t v) {
    p[0] = (uint8_t)(v >> 24); p[1] = (uint8_t)(v >> 16);
    p[2] = (uint8_t)(v >> 8); p[3] = (uint8_t)v;
}
static inline void wle32(uint8_t* p, uint32_t v) {
    p[0] = (uint8_t)v; p[1] = (uint8_t)(v >> 8);
    p[2] = (uint8_t)(v >> 16); p[3] = (uint8_t)(v >> 24);
}
static inline void wle64(uint8_t* p, uint64_t v) {
    wle32(p, (uint32_t)v);
    wle32(p + 4, (uint32_t)(v >> 32));
}
static inline long long isqrt_ll(long long v) {
    if (v <= 0) return 0;
    long long r = (long long)std::sqrt((double)v);
    while (r * r > v) --r;
    while ((r + 1) * (r + 1) <= v) ++r;
    return r;
}

static bool decode_packet(int fmt, const uint8_t* pkt, int len,
                          bft_pkt_desc* d, const uint8_t** payload,
                          int* payload_len, int decimation,
                          int cap_nsrc, int cap_src0) {
    const uint32_t SYNC = 0x5CDEC0DE;
    switch (fmt) {
    case FMT_SIMPLE:
        // simple.hpp:33: u64be seq
        if (len < 8) return false;
        std::memset(d, 0, sizeof(*d));
        d->seq = (long long)be64(pkt);
        d->nsrc = 1;
        d->nchan = 1;
        *payload = pkt + 8;
        *payload_len = len - 8;
        return d->seq >= 0;
    case FMT_CHIPS:
        // chips_hdr_type (chips.hpp:33-43): u8 roach(1-based), u8 gbe,
        // u8 nchan, u8 nsubband, u8 subband, u8 nroach, u16be chan0,
        // u64be seq(1-based)
        if (len < 16) return false;
        std::memset(d, 0, sizeof(*d));
        d->src = (int)pkt[0] - 1;
        d->tuning = pkt[1];
        d->nchan = pkt[2];
        d->nsrc = pkt[5];
        d->chan0 = be16(pkt + 6);
        d->seq = (long long)be64(pkt + 8) - 1;
        *payload = pkt + 16;
        *payload_len = len - 16;
        return d->seq >= 0 && d->chan0 >= 0;
    case FMT_TBN: {
        // tbn_hdr_type (tbn.hpp:35-42): u32le sync, u32be framecount,
        // u32be tuning, u16be tbn_id(1-based), u16be gain,
        // u64be time_tag; frame size 1048
        if (len != 1048) return false;
        if (le32(pkt) != SYNC) return false;
        std::memset(d, 0, sizeof(*d));
        uint16_t id = be16(pkt + 12);
        d->src = (int)(id & 1023) - 1;
        d->tuning = (int)be16(pkt + 8) << 16 | be16(pkt + 10);
        d->gain = be16(pkt + 14);
        d->time_tag = (long long)be64(pkt + 16);
        d->decimation = decimation > 0 ? decimation : 1;
        d->seq = d->time_tag / d->decimation / 512;
        d->nchan = 1;
        *payload = pkt + 24;
        *payload_len = len - 24;
        return d->time_tag >= 0 && ((id >> 15) & 1) == 0;
    }
    case FMT_DRX:
    case FMT_DRX8: {
        // drx_hdr_type (drx.hpp:36-45): u32le sync, ID in first byte
        // of the frame_count_word, u32be secs, u16be decim, u16be
        // time_offset, u64be time_tag, u32be tuning_word, u32be flags
        int frame = (fmt == FMT_DRX) ? 4128 : 8224;
        if (len != frame) return false;
        if (le32(pkt) != SYNC) return false;
        std::memset(d, 0, sizeof(*d));
        int id = pkt[4];
        int tune = ((id >> 3) & 0x7) - 1;
        int pol = (id >> 7) & 0x1;
        d->src = (tune << 1) | pol;
        d->decimation = be16(pkt + 12);
        if (d->decimation <= 0) d->decimation = 1;
        d->time_tag = (long long)be64(pkt + 16) - be16(pkt + 14);
        d->seq = d->time_tag / d->decimation / 4096;
        // like the Python decoder, tuning_word belongs to tuning slot 0
        // for the first tuning pair and slot 1 otherwise (drx.hpp:88-92)
        if (d->src / 2 == 0)
            d->tuning = (int)((uint32_t)be16(pkt + 24) << 16 |
                              be16(pkt + 26));
        else
            d->tuning1 = (int)((uint32_t)be16(pkt + 24) << 16 |
                               be16(pkt + 26));
        d->nchan = 1;
        *payload = pkt + 32;
        *payload_len = len - 32;
        return d->src >= 0 && d->time_tag >= 0 &&
               ((id >> 6) & 0x1) == 0;
    }
    case FMT_IBEAM: {
        // ibeam.hpp:56-81 (IBeamFormat): u8 server(1-based), u8 gbe,
        // u8 nchan, u8 nbeam, u8 nserver, u16be chan0(global),
        // u64be seq(1-based); 15 bytes total
        if (len < 15) return false;
        std::memset(d, 0, sizeof(*d));
        d->src = (int)pkt[0] - 1;
        d->tuning = pkt[1];
        d->nchan = pkt[2];
        d->beam = pkt[3];
        d->nsrc = pkt[4];
        d->chan0 = (int)be16(pkt + 5) - d->nchan * d->src;
        d->seq = (long long)be64(pkt + 7) - 1;
        *payload = pkt + 15;
        *payload_len = len - 15;
        return d->seq >= 0;
    }
    case FMT_COR: {
        // cor.hpp:62-97 (CorFormat): u32le sync, u32be fcw
        // (flag|nchan_decim|nserver|server), u32be secs, u16be
        // first_chan, u16be gain, u64be time_tag, u32be navg,
        // u16be stand0(1b), u16be stand1(1b); src0 in baseline units
        if (len < 32) return false;
        if (le32(pkt) != SYNC) return false;
        std::memset(d, 0, sizeof(*d));
        uint32_t fcw = be32(pkt + 4);
        int nchan_decim = (fcw >> 16) & 0xFF;
        int nserver = (fcw >> 8) & 0xFF;
        if (nserver < 1) nserver = 1;
        int server = fcw & 0xFF;
        d->gain = be16(pkt + 14);
        d->time_tag = (long long)be64(pkt + 16);
        long long navg = (long long)be32(pkt + 24);
        if (navg < 1) navg = 1;
        int stand0 = (int)be16(pkt + 28) - 1;
        int stand1 = (int)be16(pkt + 30) - 1;
        int nchan_pkt = (len - 32) / (8 * 4);
        long long nstand =
            (isqrt_ll(8LL * cap_nsrc / nserver + 1) - 1) / 2;
        long long navg100 = navg / 100;
        if (navg100 < 1) navg100 = 1;
        d->seq = d->time_tag / 196000000LL / navg100;
        d->decimation = (int)navg;
        d->src = (int)((stand0 * (2 * (nstand - 1) + 1 - stand0) / 2 +
                        stand1 + 1 - cap_src0) * nserver + (server - 1));
        d->nsrc = cap_nsrc;
        d->nchan = nchan_pkt;
        d->chan0 = (int)be16(pkt + 12) -
                   nchan_decim * nchan_pkt * (server - 1);
        int srv1 = server - 1;
        d->tuning = (nserver << 8) | (srv1 > 0 ? srv1 : 0);
        *payload = pkt + 32;
        *payload_len = len - 32;
        return true;
    }
    case FMT_PBEAM: {
        // pbeam.hpp:58-84 (PBeamFormat): u8 server(1b), u8 beam(1b),
        // u8 gbe, u8 nchan, u8 nbeam, u8 nserver, u16be navg,
        // u16be chan0, u64be wire_seq; src0 in wire-beam units
        if (len < 18) return false;
        std::memset(d, 0, sizeof(*d));
        int server = pkt[0];
        int beam = pkt[1];
        d->tuning = pkt[2];
        d->nchan = pkt[3];
        d->beam = pkt[4];
        int nserver = pkt[5];
        if (nserver < 1) nserver = 1;
        int navg = be16(pkt + 6);
        if (navg < 1) navg = 1;
        uint64_t wseq = be64(pkt + 10);
        d->seq = (long long)(wseq / (uint64_t)navg);
        d->time_tag = (long long)wseq;
        d->decimation = navg;
        d->src = (beam - cap_src0) * nserver + (server - 1);
        d->chan0 = (int)be16(pkt + 8) - d->nchan * d->src;
        *payload = pkt + 18;
        *payload_len = len - 18;
        return true;
    }
    case FMT_SNAP2: {
        // snap2.hpp:70-103 (Snap2Format, big-endian as the decoder's
        // be*toh reads): u64 seq, u32 sync_time, u16 npol, u16
        // npol_tot, u16 nchan, u16 nchan_tot, u32 chan_block_id,
        // u32 chan0, u32 pol0
        if (len < 32) return false;
        std::memset(d, 0, sizeof(*d));
        d->seq = (long long)be64(pkt);
        d->time_tag = (long long)be32(pkt + 8);
        int npol = be16(pkt + 12);
        if (npol < 1) npol = 1;
        int npol_tot = be16(pkt + 14);
        int nchan = be16(pkt + 16);
        if (nchan < 1) nchan = 1;
        int nchan_tot = be16(pkt + 18);
        uint32_t chan_block_id = be32(pkt + 20);
        uint32_t chan0w = be32(pkt + 24);
        uint32_t pol0 = be32(pkt + 28);
        int npol_blocks = npol_tot / npol;
        if (npol_blocks < 1) npol_blocks = 1;
        int nchan_blocks = nchan_tot / nchan;
        if (nchan_blocks < 1) nchan_blocks = 1;
        d->tuning = (int)chan0w;
        d->nsrc = npol_blocks * nchan_blocks;
        d->nchan = nchan;
        d->chan0 = (int)chan_block_id * nchan;
        d->nchan_tot = nchan_tot;
        d->npol = npol;
        d->npol_tot = npol_tot;
        d->pol0 = (int)pol0;
        d->src = (int)(pol0 / (uint32_t)npol) +
                 (int)chan_block_id * npol_blocks;
        *payload = pkt + 32;
        *payload_len = len - 32;
        return true;
    }
    case FMT_VDIF: {
        // vdif.hpp:119-168 (VdifFormat): 4 u32le words with LSB-first
        // bitfields; non-legacy frames carry a 16-byte extended header.
        // seq = secs*fps + frame_in_second; fps rides the capture's
        // decimation parameter (stream-learned in the reference)
        if (len < 16) return false;
        uint32_t w0 = le32(pkt), w1 = le32(pkt + 4);
        uint32_t w2 = le32(pkt + 8), w3 = le32(pkt + 12);
        if (w0 & 0x80000000u) return false;    // invalid flag
        int legacy = (w0 >> 30) & 1;
        int off = legacy ? 16 : 32;
        if (len < off) return false;
        std::memset(d, 0, sizeof(*d));
        long long secs = (long long)(w0 & 0x3FFFFFFFu);
        long long fnum = (long long)(w1 & 0xFFFFFFu);
        int ref_epoch = (w1 >> 24) & 0x3F;
        int log2_nchan = (w2 >> 24) & 0x1F;
        if (log2_nchan > 30) return false;   // wire-controlled field;
                                             // 1<<31 would overflow int
        int thread_id = (w3 >> 16) & 0x3FF;
        int nbit = ((w3 >> 26) & 0x1F) + 1;
        int is_complex = (int)((w3 >> 31) & 1);
        long long fps = decimation > 0 ? decimation : 1;
        d->seq = secs * fps + fnum;
        d->time_tag = secs;
        d->src = thread_id;
        d->chan0 = 1 << log2_nchan;
        d->nchan = (len - off) / 8;
        d->tuning = (ref_epoch << 16) | (nbit << 8) | is_complex;
        *payload = pkt + off;
        *payload_len = len - off;
        return true;
    }
    case FMT_TBF: {
        // tbf.hpp (TbfFormat): u32le sync, u32be fcw(flag 0x01),
        // u32be secs, u16be first_chan, u16be nstand, u64be time_tag;
        // 'src' rides first_chan
        if (len < 24) return false;
        if (le32(pkt) != SYNC) return false;
        std::memset(d, 0, sizeof(*d));
        d->src = be16(pkt + 12);
        d->nsrc = be16(pkt + 14);
        d->time_tag = (long long)be64(pkt + 16);
        d->seq = d->time_tag;
        d->nchan = 1;
        *payload = pkt + 24;
        *payload_len = len - 24;
        return d->seq >= 0;
    }
    case FMT_VBEAM: {
        // vbeam.hpp (VBeamFormat): u64le sync 0xAABBCCDD00000000,
        // u64le sync_time, u64be time_tag, f64le bw, f64le sfreq,
        // u32le nchan, u32le chan0, u32le npol
        if (len < 52) return false;
        if (le64(pkt) != 0xAABBCCDD00000000ull) return false;
        std::memset(d, 0, sizeof(*d));
        d->time_tag = (long long)le64(pkt + 8);
        d->seq = (long long)be64(pkt + 16);
        int nchan = (int)le32(pkt + 40);
        d->nchan = nchan < 1 ? 1 : nchan;
        d->chan0 = (int)le32(pkt + 44);
        d->npol = (int)le32(pkt + 48);
        *payload = pkt + 52;
        *payload_len = len - 52;
        return d->seq >= 0;
    }
    }
    return false;
}

struct Buf {
    long long start = 0;        // first seq slot
    long long span_id = -1;
    long long begin = 0;        // ring byte offset
    std::vector<uint8_t> got;   // ntime * nsrc
};

struct Transmit {
    int fmt = FMT_SIMPLE;
    int sockfd = -1;
    long long rate_pps = 0;     // 0 = unlimited
    double next_time = 0.0;
    int nbeam = 1;              // pbeam/ibeam filler parameter
    // vdif filler parameters (mirror VdifFormat defaults)
    int vdif_fps = 25600;
    int vdif_legacy = 0;
    int vdif_log2_nchan = 0;
    int vdif_nbit = 8;
    int vdif_complex = 1;
    int vdif_station = 0;
    int vdif_epoch = 0;
};

// wire header length the filler writes for each format
static int tx_header_len(const Transmit* t) {
    switch (t->fmt) {
    case FMT_SIMPLE: return 8;
    case FMT_CHIPS:  return 16;
    case FMT_TBN:    return 24;
    case FMT_DRX:
    case FMT_DRX8:   return 32;
    case FMT_IBEAM:  return 15;
    case FMT_COR:    return 32;
    case FMT_PBEAM:  return 18;
    case FMT_SNAP2:  return 32;
    case FMT_VDIF:   return t->vdif_legacy ? 16 : 32;
    case FMT_TBF:    return 24;
    case FMT_VBEAM:  return 52;
    }
    return -1;
}

struct Capture {
    int fmt = FMT_SIMPLE;
    int sockfd = -1;
    void* ring = nullptr;
    int nsrc = 1;
    int src0 = 0;
    int payload_size = 0;
    int buffer_ntime = 0;
    int slot_ntime = 0;
    int timeout_ms = 200;
    int batch = 128;
    int decimation = 1;        // TBN seq derivation (stream parameter)

    bft_header_cb header_cb = nullptr;
    void* cb_user = nullptr;

    bool writing = false;
    void* seq = nullptr;
    long long seq0 = -1;
    std::vector<Buf> bufs;      // sliding window, oldest first (max 2)

    long long ngood_bytes = 0;
    long long nmissing_bytes = 0;
    long long ninvalid = 0;
    long long nignored = 0;
    std::vector<long long> src_ngood;

    // recvmmsg state
    std::vector<uint8_t> rxbuf;
    std::vector<mmsghdr> hdrs;
    std::vector<iovec> iovs;

    long long span_nbyte() const {
        return (long long)buffer_ntime * nsrc * payload_size;
    }
};

static uint8_t* span_ptr(Capture* c, long long begin, long long nbyte) {
    unsigned char* base = nullptr;
    long long size = 0, ghost = 0, nrl = 0;
    if (bft_ring_geometry(c->ring, &base, &size, &ghost, &nrl) != BFT_OK
        || !base || size <= 0)
        return nullptr;
    (void)nbyte;
    return base + (begin % size);
}

static int open_buf(Capture* c, long long start) {
    Buf b;
    b.start = start;
    if (bft_ring_reserve(c->ring, c->span_nbyte(), 0, &b.begin,
                         &b.span_id) != BFT_OK)
        return BFT_ERR_STATE;
    uint8_t* p = span_ptr(c, b.begin, c->span_nbyte());
    if (!p) return BFT_ERR_STATE;
    std::memset(p, 0, (size_t)c->span_nbyte());
    b.got.assign((size_t)c->buffer_ntime * c->nsrc, 0);
    c->bufs.push_back(std::move(b));
    return BFT_OK;
}

static void commit_oldest(Capture* c) {
    Buf& b = c->bufs.front();
    uint8_t* p = span_ptr(c, b.begin, c->span_nbyte());
    // per-source loss accounting + >50%-loss blanking
    // (reference: packet_capture.hpp:505-534)
    for (int s = 0; s < c->nsrc; ++s) {
        long long good = 0;
        for (int t = 0; t < c->buffer_ntime; ++t)
            good += b.got[(size_t)t * c->nsrc + s];
        c->src_ngood[s] += good * c->payload_size;
        c->ngood_bytes += good * c->payload_size;
        c->nmissing_bytes +=
            (long long)(c->buffer_ntime - good) * c->payload_size;
        if (good * 2 < c->buffer_ntime && p) {
            for (int t = 0; t < c->buffer_ntime; ++t)
                std::memset(p + ((size_t)t * c->nsrc + s) *
                                    c->payload_size,
                            0, (size_t)c->payload_size);
        }
    }
    bft_ring_commit(c->ring, b.span_id, c->span_nbyte());
    c->bufs.erase(c->bufs.begin());
}

static int begin_sequence(Capture* c, const bft_pkt_desc* d) {
    if (!c->writing) {
        bft_ring_begin_writing(c->ring);
        c->writing = true;
    }
    long long time_tag = 0;
    char hdr[65536];
    char name[256];
    hdr[0] = 0;
    // the callback sees src rebased by src0, like the Python engine
    // (composed-src formats already applied src0 in the decoder)
    bft_pkt_desc dd = *d;
    if (!src0_in_decoder(c->fmt)) dd.src -= c->src0;
    std::snprintf(name, sizeof(name), "capture-%lld", d->seq);
    if (c->header_cb) {
        if (c->header_cb(c->cb_user, &dd, &time_tag, name,
                         (int)sizeof(name), hdr, (int)sizeof(hdr)) != 0)
            return BFT_ERR_STATE;
    }
    if (bft_ring_begin_sequence(c->ring, name, time_tag, hdr,
                                (long long)std::strlen(hdr), 1,
                                &c->seq) != BFT_OK)
        return BFT_ERR_STATE;
    c->seq0 = (d->seq / c->slot_ntime) * c->slot_ntime;
    c->bufs.clear();
    return BFT_OK;
}

// process one decoded packet; returns true if a span was committed
static bool process_packet(Capture* c, const bft_pkt_desc* d,
                           const uint8_t* payload, int plen,
                           bool* started) {
    bool committed = false;
    int src = d->src - (src0_in_decoder(c->fmt) ? 0 : c->src0);
    if (src < 0 || src >= c->nsrc) {
        ++c->nignored;
        return false;
    }
    if (c->seq0 < 0) {
        if (begin_sequence(c, d) != BFT_OK) return false;
        *started = true;
    }
    long long off = d->seq - c->seq0;
    if (off < 0) {
        ++c->nignored;
        return false;
    }
    for (;;) {
        long long last_end = c->bufs.empty()
            ? 0 : c->bufs.back().start + c->buffer_ntime;
        if (off < last_end) break;
        if (c->bufs.size() == 2) {
            commit_oldest(c);
            committed = true;
        }
        if (open_buf(c, last_end) != BFT_OK) return committed;
    }
    for (auto& b : c->bufs) {
        if (b.start <= off && off < b.start + c->buffer_ntime) {
            long long t = off - b.start;
            uint8_t* p = span_ptr(c, b.begin, c->span_nbyte());
            if (p) {
                int n = plen < c->payload_size ? plen : c->payload_size;
                std::memcpy(p + ((size_t)t * c->nsrc + src) *
                                    c->payload_size,
                            payload, (size_t)n);
                b.got[(size_t)t * c->nsrc + src] = 1;
            }
            break;
        } else if (off < b.start) {
            ++c->nignored;   // too late
            break;
        }
    }
    return committed;
}

}  // namespace

extern "C" {

int bft_capture_create(void** out, int fmt, int sockfd, void* ring,
                       int nsrc, int src0, int payload_size,
                       int buffer_ntime, int slot_ntime) {
    if (!out || !ring || nsrc <= 0 || payload_size <= 0 ||
        buffer_ntime <= 0 || slot_ntime <= 0)
        return BFT_ERR_INVALID;
    if (fmt < FMT_SIMPLE || fmt > FMT_VBEAM) return BFT_ERR_INVALID;
    auto* c = new Capture();
    c->fmt = fmt;
    c->sockfd = sockfd;
    c->ring = ring;
    c->nsrc = nsrc;
    c->src0 = src0;
    c->payload_size = payload_size;
    c->buffer_ntime = buffer_ntime;
    c->slot_ntime = slot_ntime;
    c->src_ngood.assign(nsrc, 0);
    // size the ring for the span gulps (writer side owns geometry)
    bft_ring_resize(ring, c->span_nbyte(), 4 * c->span_nbyte(), 1);
    int pkt_cap = payload_size + 1024;
    c->rxbuf.assign((size_t)c->batch * pkt_cap, 0);
    c->hdrs.assign(c->batch, mmsghdr());
    c->iovs.assign(c->batch, iovec());
    for (int i = 0; i < c->batch; ++i) {
        c->iovs[i].iov_base = c->rxbuf.data() + (size_t)i * pkt_cap;
        c->iovs[i].iov_len = pkt_cap;
        std::memset(&c->hdrs[i], 0, sizeof(mmsghdr));
        c->hdrs[i].msg_hdr.msg_iov = &c->iovs[i];
        c->hdrs[i].msg_hdr.msg_iovlen = 1;
    }
    *out = c;
    return BFT_OK;
}

int bft_capture_set_header_callback(void* cap, bft_header_cb fn,
                                    void* user) {
    auto* c = static_cast<Capture*>(cap);
    if (!c) return BFT_ERR_INVALID;
    c->header_cb = fn;
    c->cb_user = user;
    return BFT_OK;
}

int bft_capture_set_decimation(void* cap, int decim) {
    auto* c = static_cast<Capture*>(cap);
    if (!c || decim <= 0) return BFT_ERR_INVALID;
    c->decimation = decim;
    return BFT_OK;
}

int bft_capture_set_timeout_ms(void* cap, int ms) {
    auto* c = static_cast<Capture*>(cap);
    if (!c) return BFT_ERR_INVALID;
    c->timeout_ms = ms;
    return BFT_OK;
}

// Run until one span commits (or timeout).  *status_out gets a
// CAPTURE_* code like the Python engine's recv().
int bft_capture_recv(void* cap, int* status_out) {
    auto* c = static_cast<Capture*>(cap);
    if (!c || !status_out) return BFT_ERR_INVALID;
    bool started = false;
    bool committed = false;
    int pkt_cap = c->payload_size + 1024;
    while (!committed) {
        struct pollfd pfd = {c->sockfd, POLLIN, 0};
        int pr = poll(&pfd, 1, c->timeout_ms);   // -1 = block forever
        if (pr <= 0) {
            *status_out = (c->seq0 < 0) ? CAPTURE_NO_DATA
                                        : CAPTURE_INTERRUPTED;
            return BFT_OK;
        }
        int n = recvmmsg(c->sockfd, c->hdrs.data(), c->batch,
                         MSG_DONTWAIT, nullptr);
        if (n < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK ||
                errno == EINTR)
                continue;
            return BFT_ERR_STATE;
        }
        for (int i = 0; i < n; ++i) {
            const uint8_t* pkt =
                c->rxbuf.data() + (size_t)i * pkt_cap;
            int len = (int)c->hdrs[i].msg_len;
            bft_pkt_desc d;
            const uint8_t* payload = nullptr;
            int plen = 0;
            if (!decode_packet(c->fmt, pkt, len, &d, &payload, &plen,
                               c->decimation, c->nsrc, c->src0)) {
                ++c->ninvalid;
                continue;
            }
            committed |= process_packet(c, &d, payload, plen, &started);
        }
    }
    *status_out = started ? CAPTURE_STARTED : CAPTURE_CONTINUED;
    return BFT_OK;
}

int bft_capture_flush(void* cap) {
    auto* c = static_cast<Capture*>(cap);
    if (!c) return BFT_ERR_INVALID;
    while (!c->bufs.empty()) commit_oldest(c);
    return BFT_OK;
}

int bft_capture_end(void* cap) {
    auto* c = static_cast<Capture*>(cap);
    if (!c) return BFT_ERR_INVALID;
    bft_capture_flush(c);
    if (c->seq) {
        bft_ring_end_sequence(c->ring, c->seq);
        c->seq = nullptr;
    }
    if (c->writing) {
        bft_ring_end_writing(c->ring);
        c->writing = false;
    }
    c->seq0 = -1;
    return BFT_OK;
}

int bft_capture_stats(void* cap, long long* ngood, long long* nmissing,
                      long long* ninvalid, long long* nignored) {
    auto* c = static_cast<Capture*>(cap);
    if (!c) return BFT_ERR_INVALID;
    if (ngood) *ngood = c->ngood_bytes;
    if (nmissing) *nmissing = c->nmissing_bytes;
    if (ninvalid) *ninvalid = c->ninvalid;
    if (nignored) *nignored = c->nignored;
    return BFT_OK;
}

int bft_capture_src_ngood(void* cap, long long* out, int n) {
    auto* c = static_cast<Capture*>(cap);
    if (!c || !out) return BFT_ERR_INVALID;
    for (int i = 0; i < n && i < (int)c->src_ngood.size(); ++i)
        out[i] = c->src_ngood[i];
    return BFT_OK;
}

int bft_capture_destroy(void* cap) {
    auto* c = static_cast<Capture*>(cap);
    delete c;
    return BFT_OK;
}

// ---------------------------------------------------------------------------
// Native packet writer: header fill + sendmmsg batches
// (reference: src/packet_writer.hpp:59-580 — HeaderInfo + per-format
// fillers + senders + token-bucket rate limiter)
// ---------------------------------------------------------------------------

int bft_transmit_create(void** out, int fmt, int sockfd) {
    if (!out) return BFT_ERR_INVALID;
    if (fmt < FMT_SIMPLE || fmt > FMT_VBEAM) return BFT_ERR_INVALID;
    auto* t = new Transmit();
    t->fmt = fmt;
    t->sockfd = sockfd;
    *out = t;
    return BFT_OK;
}

int bft_transmit_set_nbeam(void* tr, int nbeam) {
    auto* t = static_cast<Transmit*>(tr);
    if (!t || nbeam <= 0) return BFT_ERR_INVALID;
    t->nbeam = nbeam;
    return BFT_OK;
}

int bft_transmit_set_vdif(void* tr, int fps, int legacy, int log2_nchan,
                          int nbit, int is_complex, int station_id,
                          int ref_epoch) {
    auto* t = static_cast<Transmit*>(tr);
    if (!t || fps <= 0 || nbit <= 0) return BFT_ERR_INVALID;
    t->vdif_fps = fps;
    t->vdif_legacy = legacy ? 1 : 0;
    t->vdif_log2_nchan = log2_nchan;
    t->vdif_nbit = nbit;
    t->vdif_complex = is_complex ? 1 : 0;
    t->vdif_station = station_id;
    t->vdif_epoch = ref_epoch;
    return BFT_OK;
}

int bft_transmit_set_rate(void* tr, long long pps) {
    auto* t = static_cast<Transmit*>(tr);
    if (!t) return BFT_ERR_INVALID;
    t->rate_pps = pps;
    t->next_time = 0.0;
    return BFT_OK;
}

// Send nseq*nsrc packets: packet (i, j) carries seq0 + i*seq_inc and
// src0 + j*src_inc with payload data[i, j, :payload_size].
int bft_transmit_send(void* tr, long long seq0, long long seq_inc,
                      int src0, int src_inc, int hdr_nsrc, int chan0,
                      int nchan, int tuning, int gain, int decimation,
                      long long framecount0,
                      const unsigned char* data, int nseq, int nsrc,
                      int payload_size, long long* nsent_out) {
    auto* t = static_cast<Transmit*>(tr);
    if (!t || !data || nseq <= 0 || nsrc <= 0 || payload_size <= 0)
        return BFT_ERR_INVALID;
    const int hdr_len = tx_header_len(t);
    if (hdr_len < 0) return BFT_ERR_INVALID;
    if (decimation < 1) decimation = 1;
    long long framecount = framecount0;
    const int pkt_len = hdr_len + payload_size;
    const int BATCH = 64;
    std::vector<uint8_t> bufs((size_t)BATCH * pkt_len);
    std::vector<mmsghdr> hdrs(BATCH);
    std::vector<iovec> iovs(BATCH);
    for (int k = 0; k < BATCH; ++k) {
        iovs[k].iov_base = bufs.data() + (size_t)k * pkt_len;
        iovs[k].iov_len = pkt_len;
        std::memset(&hdrs[k], 0, sizeof(mmsghdr));
        hdrs[k].msg_hdr.msg_iov = &iovs[k];
        hdrs[k].msg_hdr.msg_iovlen = 1;
    }
    long long nsent = 0;
    int k = 0;
    auto flush = [&]() -> bool {
        int off = 0;
        while (off < k) {
            int n = sendmmsg(t->sockfd, hdrs.data() + off, k - off, 0);
            if (n < 0) {
                if (errno == EINTR) continue;
                if (errno == EAGAIN || errno == EWOULDBLOCK ||
                    errno == ENOBUFS) {
                    // wait for buffer space, then retry
                    struct pollfd pfd = {t->sockfd, POLLOUT, 0};
                    poll(&pfd, 1, 100);
                    continue;
                }
                return false;
            }
            nsent += n;
            off += n;
            if (t->rate_pps > 0 && n > 0) {
                // token bucket charged by packets ACTUALLY sent
                timespec ts;
                clock_gettime(CLOCK_MONOTONIC, &ts);
                double now = ts.tv_sec + ts.tv_nsec * 1e-9;
                if (t->next_time <= 0.0) t->next_time = now;
                t->next_time += (double)n / (double)t->rate_pps;
                double delay = t->next_time - now;
                if (delay > 0) {
                    timespec d;
                    d.tv_sec = (time_t)delay;
                    d.tv_nsec = (long)((delay - (time_t)delay) * 1e9);
                    nanosleep(&d, nullptr);
                }
            }
        }
        k = 0;
        return true;
    };
    for (int i = 0; i < nseq; ++i) {
        for (int j = 0; j < nsrc; ++j) {
            uint8_t* p = bufs.data() + (size_t)k * pkt_len;
            long long seq = seq0 + i * seq_inc;
            int src = src0 + j * src_inc;
            const uint32_t SYNC = 0x5CDEC0DE;
            switch (t->fmt) {
            case FMT_SIMPLE:
                wbe64(p, (uint64_t)seq);
                break;
            case FMT_CHIPS:   // mirror CHIPSHeaderFiller
                p[0] = (uint8_t)(src + 1);
                p[1] = (uint8_t)tuning;
                p[2] = (uint8_t)nchan;
                p[3] = 1;
                p[4] = 0;
                p[5] = (uint8_t)hdr_nsrc;
                wbe16(p + 6, (uint16_t)chan0);
                wbe64(p + 8, (uint64_t)seq);
                break;
            case FMT_TBN:     // TBNHeaderFiller (tbn.hpp:124-141)
                wle32(p, SYNC);
                wbe32(p + 4, (uint32_t)(framecount & 0xFFFFFF));
                wbe32(p + 8, (uint32_t)tuning);
                wbe16(p + 12, (uint16_t)((src + 1) & 0x3FFF));
                wbe16(p + 14, (uint16_t)gain);
                wbe64(p + 16, (uint64_t)seq);
                break;
            case FMT_DRX:     // DRXHeaderFiller (drx.hpp:156-172):
            case FMT_DRX8:    // src carries the raw wire ID byte
                wle32(p, SYNC);
                p[4] = (uint8_t)(src & 0xBF);
                p[5] = p[6] = p[7] = 0;      // frame count
                wbe32(p + 8, 0);             // seconds
                wbe16(p + 12, (uint16_t)decimation);
                wbe16(p + 14, 0);            // time offset
                wbe64(p + 16, (uint64_t)seq);
                wbe32(p + 24, (uint32_t)tuning);
                wbe32(p + 28, 0);            // flags
                break;
            case FMT_IBEAM: { // IBeamHeaderFiller (ibeam.hpp:92-109)
                p[0] = (uint8_t)(src + 1);
                p[1] = (uint8_t)tuning;
                p[2] = (uint8_t)nchan;
                p[3] = (uint8_t)t->nbeam;
                p[4] = (uint8_t)hdr_nsrc;
                wbe16(p + 5, (uint16_t)((chan0 + nchan * src) &
                                        0xFFFF));
                wbe64(p + 7, (uint64_t)seq);
                break;
            }
            case FMT_COR: {   // CORHeaderFiller (cor.hpp:117-146):
                // recover the 1-based stand pair from the flat
                // baseline index (matches CorFormat.pack)
                long long n = (isqrt_ll(8LL * hdr_nsrc + 1) - 1) / 2;
                double b = (double)(2 + 2 * (n - 1) + 1);
                double rad = b * b - 8.0 * src;
                if (rad < 0.0) {
                    // src outside the baseline range for hdr_nsrc;
                    // the Python codec raises here — fail the batch
                    // instead of emitting NaN-derived stand indices
                    if (nsent_out) *nsent_out = nsent;
                    return BFT_ERR_INVALID;
                }
                long long s0 = (long long)((b - std::sqrt(rad)) / 2.0);
                long long s1 = src -
                    s0 * (2 * (n - 1) + 1 - s0) / 2;
                wle32(p, SYNC);
                wbe32(p + 4, (0x02u << 24) |
                             ((uint32_t)tuning & 0xFFFFFF));
                wbe32(p + 8, 0);
                wbe16(p + 12, (uint16_t)chan0);
                wbe16(p + 14, (uint16_t)gain);
                wbe64(p + 16, (uint64_t)seq);
                wbe32(p + 24, (uint32_t)decimation);
                wbe16(p + 28, (uint16_t)((s0 + 1) & 0xFFFF));
                wbe16(p + 30, (uint16_t)((s1 + 1) & 0xFFFF));
                break;
            }
            case FMT_PBEAM: { // PBeamHeaderFiller (pbeam.hpp:126-147)
                int nserver = hdr_nsrc / t->nbeam;
                if (nserver < 1) nserver = 1;
                p[0] = (uint8_t)((src % nserver) + 1);
                p[1] = (uint8_t)((src / nserver) + 1);
                p[2] = (uint8_t)tuning;
                p[3] = (uint8_t)nchan;
                p[4] = (uint8_t)t->nbeam;
                p[5] = (uint8_t)nserver;
                wbe16(p + 6, (uint16_t)decimation);
                wbe16(p + 8, (uint16_t)chan0);
                wbe64(p + 10, (uint64_t)seq);
                break;
            }
            case FMT_SNAP2: { // Snap2Format.pack (decoder-readable
                // big-endian; npol defaults to 2 like the Python side)
                int npol = 2, npol_tot = 2;
                int nchan_tot = nchan * hdr_nsrc;
                wbe64(p, (uint64_t)seq);
                wbe32(p + 8, 0);             // sync_time
                wbe16(p + 12, (uint16_t)npol);
                wbe16(p + 14, (uint16_t)npol_tot);
                wbe16(p + 16, (uint16_t)nchan);
                wbe16(p + 18, (uint16_t)nchan_tot);
                wbe32(p + 20, (uint32_t)src);    // chan_block_id
                wbe32(p + 24, (uint32_t)chan0);
                wbe32(p + 28, 0);            // pol0
                break;
            }
            case FMT_VDIF: {  // VdifFormat.pack (LSB-first bitfields
                // in u32le words; 16-byte zero extended header unless
                // legacy)
                long long secs = seq / t->vdif_fps;
                long long fnum = seq % t->vdif_fps;
                uint32_t w0 = (uint32_t)(secs & 0x3FFFFFFF) |
                              (t->vdif_legacy ? (1u << 30) : 0);
                uint32_t w1 = (uint32_t)(fnum & 0xFFFFFF) |
                              (((uint32_t)t->vdif_epoch & 0x3F) << 24);
                uint32_t frame_len8 =
                    (uint32_t)((hdr_len + payload_size) / 8);
                uint32_t w2 = (frame_len8 & 0xFFFFFF) |
                              (((uint32_t)t->vdif_log2_nchan & 0x1F)
                               << 24);
                uint32_t w3 = ((uint32_t)t->vdif_station & 0xFFFF) |
                              (((uint32_t)src & 0x3FF) << 16) |
                              ((((uint32_t)t->vdif_nbit - 1) & 0x1F)
                               << 26) |
                              (t->vdif_complex ? (1u << 31) : 0);
                wle32(p, w0);
                wle32(p + 4, w1);
                wle32(p + 8, w2);
                wle32(p + 12, w3);
                if (!t->vdif_legacy) std::memset(p + 16, 0, 16);
                break;
            }
            case FMT_TBF:     // TBFHeaderFiller (tbf.hpp:42-59):
                // 'src' rides first_chan
                wle32(p, SYNC);
                wbe32(p + 4, (0x01u << 24) |
                             (uint32_t)(framecount & 0xFFFFFF));
                wbe32(p + 8, 0);
                wbe16(p + 12, (uint16_t)(src & 0xFFFF));
                wbe16(p + 14, (uint16_t)(hdr_nsrc & 0xFFFF));
                wbe64(p + 16, (uint64_t)seq);
                break;
            case FMT_VBEAM:   // VBeamHeaderFiller (vbeam.hpp:44-57)
                wle64(p, 0xAABBCCDD00000000ull);
                wle64(p + 8, 0);             // sync_time / time_tag
                wbe64(p + 16, (uint64_t)seq);
                std::memset(p + 24, 0, 16);  // bw, sfreq (f64le zeros)
                wle32(p + 40, (uint32_t)nchan);
                wle32(p + 44, (uint32_t)chan0);
                wle32(p + 48, 0);            // npol
                break;
            }
            ++framecount;
            std::memcpy(p + hdr_len,
                        data + ((size_t)i * nsrc + j) * payload_size,
                        (size_t)payload_size);
            if (++k == BATCH && !flush()) {
                if (nsent_out) *nsent_out = nsent;
                return BFT_ERR_STATE;
            }
        }
    }
    if (k && !flush()) {
        if (nsent_out) *nsent_out = nsent;
        return BFT_ERR_STATE;
    }
    (void)gain;
    if (nsent_out) *nsent_out = nsent;
    return BFT_OK;
}

int bft_transmit_destroy(void* tr) {
    delete static_cast<Transmit*>(tr);
    return BFT_OK;
}

}  // extern "C"

#else  // !BFT_HAVE_CAPTURE: portable stubs so the .so builds anywhere

extern "C" {
int bft_capture_create(void**, int, int, void*, int, int, int, int,
                       int) { return BFT_ERR_INVALID; }
int bft_capture_set_header_callback(void*, bft_header_cb, void*) {
    return BFT_ERR_INVALID;
}
int bft_capture_set_timeout_ms(void*, int) { return BFT_ERR_INVALID; }
int bft_capture_set_decimation(void*, int) { return BFT_ERR_INVALID; }
int bft_capture_recv(void*, int*) { return BFT_ERR_INVALID; }
int bft_capture_flush(void*) { return BFT_ERR_INVALID; }
int bft_capture_end(void*) { return BFT_ERR_INVALID; }
int bft_capture_stats(void*, long long*, long long*, long long*,
                      long long*) { return BFT_ERR_INVALID; }
int bft_capture_src_ngood(void*, long long*, int) {
    return BFT_ERR_INVALID;
}
int bft_capture_destroy(void*) { return BFT_OK; }
int bft_transmit_create(void**, int, int) { return BFT_ERR_INVALID; }
int bft_transmit_set_rate(void*, long long) { return BFT_ERR_INVALID; }
int bft_transmit_set_nbeam(void*, int) { return BFT_ERR_INVALID; }
int bft_transmit_set_vdif(void*, int, int, int, int, int, int, int) {
    return BFT_ERR_INVALID;
}
int bft_transmit_send(void*, long long, long long, int, int, int, int,
                      int, int, int, int, long long,
                      const unsigned char*, int, int,
                      int, long long*) { return BFT_ERR_INVALID; }
int bft_transmit_destroy(void*) { return BFT_OK; }
}  // extern "C"

#endif  // BFT_HAVE_CAPTURE
