// Native UDP capture engine for bifrost_tpu.
//
// The reference's packet capture is a C++ engine: a capture loop
// receives batches of datagrams, decodes per-telescope headers, and
// scatters payloads into a sliding window of two open ring spans with
// per-source loss accounting and >50%-loss blanking
// (reference: src/packet_capture.hpp:150-607 and the recvmmsg shim
// src/Socket.hpp:145-158).  This file is the TPU build's equivalent:
// it drives the native ring through the same BFT C ABI Python uses
// (native/ring.cpp) and calls back into Python only once per sequence
// for header construction (the C->Python callback boundary the
// reference also has, packet_capture.hpp:535-540).
//
// Formats: decoders are implemented here for the formats whose wire
// layouts are hot capture paths ('simple': u64be seq + payload,
// simple.hpp:33; 'chips': chips_hdr_type, chips.hpp:33).  Other
// formats use the Python engine (identical semantics, shared tests).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

// The engine is Linux-only (recvmmsg/poll); elsewhere the ABI stubs
// return BFT_ERR_INVALID and Python auto-falls-back to its engine,
// keeping the native RING portable.
#if defined(__linux__)
#include <ctime>
#include <poll.h>
#include <sys/socket.h>
#include <sys/types.h>
#define BFT_HAVE_CAPTURE 1
#endif

#define BFT_OK 0
#define BFT_ERR_INVALID (-1)
#define BFT_ERR_STATE (-2)

// capture status codes (match bifrost_tpu.io.packet_capture)
#define CAPTURE_STARTED 1
#define CAPTURE_CONTINUED 2
#define CAPTURE_ENDED 4
#define CAPTURE_NO_DATA 8
#define CAPTURE_INTERRUPTED 16

extern "C" {
// ring ABI (native/ring.cpp)
int bft_ring_resize(void*, long long, long long, long long);
int bft_ring_geometry(void*, unsigned char**, long long*, long long*,
                      long long*);
int bft_ring_begin_writing(void*);
int bft_ring_end_writing(void*);
int bft_ring_begin_sequence(void*, const char*, long long, const char*,
                            long long, long long, void**);
int bft_ring_end_sequence(void*, void*);
int bft_ring_reserve(void*, long long, int, long long*, long long*);
int bft_ring_commit(void*, long long, long long);

typedef struct {
    long long seq;
    long long time_tag;
    int src;
    int nsrc;
    int nchan;
    int chan0;
    int tuning;
    int tuning1;
    int gain;
    int decimation;
    int payload_size;
} bft_pkt_desc;

// Python fills time_tag_out, the sequence name, and a JSON header
// (NUL-terminated, <= caps); returns 0 on success.
typedef int (*bft_header_cb)(void* user, const bft_pkt_desc* desc,
                             long long* time_tag_out, char* name_buf,
                             int name_cap, char* hdr_json, int hdr_cap);
}

#if BFT_HAVE_CAPTURE
namespace {

enum Format { FMT_SIMPLE = 0, FMT_CHIPS = 1, FMT_TBN = 2,
              FMT_DRX = 3, FMT_DRX8 = 4 };

// Decode one datagram; mirrors the Python codecs in
// bifrost_tpu/io/packet_formats.py (themselves mirrors of the
// reference decoders).  Returns false for runts/invalid packets.
static inline uint64_t be64(const uint8_t* p) {
    uint64_t v = 0;
    for (int i = 0; i < 8; ++i) v = (v << 8) | p[i];
    return v;
}
static inline uint16_t be16(const uint8_t* p) {
    return (uint16_t)((p[0] << 8) | p[1]);
}
static inline void wbe64(uint8_t* p, uint64_t v) {
    for (int i = 7; i >= 0; --i) { p[i] = (uint8_t)v; v >>= 8; }
}
static inline void wbe16(uint8_t* p, uint16_t v) {
    p[1] = (uint8_t)v;
    p[0] = (uint8_t)(v >> 8);
}

static inline uint32_t le32(const uint8_t* p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
           ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
}

static bool decode_packet(int fmt, const uint8_t* pkt, int len,
                          bft_pkt_desc* d, const uint8_t** payload,
                          int* payload_len, int decimation) {
    const uint32_t SYNC = 0x5CDEC0DE;
    switch (fmt) {
    case FMT_SIMPLE:
        // simple.hpp:33: u64be seq
        if (len < 8) return false;
        std::memset(d, 0, sizeof(*d));
        d->seq = (long long)be64(pkt);
        d->nsrc = 1;
        d->nchan = 1;
        *payload = pkt + 8;
        *payload_len = len - 8;
        return d->seq >= 0;
    case FMT_CHIPS:
        // chips_hdr_type (chips.hpp:33-43): u8 roach(1-based), u8 gbe,
        // u8 nchan, u8 nsubband, u8 subband, u8 nroach, u16be chan0,
        // u64be seq(1-based)
        if (len < 16) return false;
        std::memset(d, 0, sizeof(*d));
        d->src = (int)pkt[0] - 1;
        d->tuning = pkt[1];
        d->nchan = pkt[2];
        d->nsrc = pkt[5];
        d->chan0 = be16(pkt + 6);
        d->seq = (long long)be64(pkt + 8) - 1;
        *payload = pkt + 16;
        *payload_len = len - 16;
        return d->seq >= 0 && d->chan0 >= 0;
    case FMT_TBN: {
        // tbn_hdr_type (tbn.hpp:35-42): u32le sync, u32be framecount,
        // u32be tuning, u16be tbn_id(1-based), u16be gain,
        // u64be time_tag; frame size 1048
        if (len != 1048) return false;
        if (le32(pkt) != SYNC) return false;
        std::memset(d, 0, sizeof(*d));
        uint16_t id = be16(pkt + 12);
        d->src = (int)(id & 1023) - 1;
        d->tuning = (int)be16(pkt + 8) << 16 | be16(pkt + 10);
        d->gain = be16(pkt + 14);
        d->time_tag = (long long)be64(pkt + 16);
        d->decimation = decimation > 0 ? decimation : 1;
        d->seq = d->time_tag / d->decimation / 512;
        d->nchan = 1;
        *payload = pkt + 24;
        *payload_len = len - 24;
        return d->time_tag >= 0 && ((id >> 15) & 1) == 0;
    }
    case FMT_DRX:
    case FMT_DRX8: {
        // drx_hdr_type (drx.hpp:36-45): u32le sync, ID in first byte
        // of the frame_count_word, u32be secs, u16be decim, u16be
        // time_offset, u64be time_tag, u32be tuning_word, u32be flags
        int frame = (fmt == FMT_DRX) ? 4128 : 8224;
        if (len != frame) return false;
        if (le32(pkt) != SYNC) return false;
        std::memset(d, 0, sizeof(*d));
        int id = pkt[4];
        int tune = ((id >> 3) & 0x7) - 1;
        int pol = (id >> 7) & 0x1;
        d->src = (tune << 1) | pol;
        d->decimation = be16(pkt + 12);
        if (d->decimation <= 0) d->decimation = 1;
        d->time_tag = (long long)be64(pkt + 16) - be16(pkt + 14);
        d->seq = d->time_tag / d->decimation / 4096;
        // like the Python decoder, tuning_word belongs to tuning slot 0
        // for the first tuning pair and slot 1 otherwise (drx.hpp:88-92)
        if (d->src / 2 == 0)
            d->tuning = (int)((uint32_t)be16(pkt + 24) << 16 |
                              be16(pkt + 26));
        else
            d->tuning1 = (int)((uint32_t)be16(pkt + 24) << 16 |
                               be16(pkt + 26));
        d->nchan = 1;
        *payload = pkt + 32;
        *payload_len = len - 32;
        return d->src >= 0 && d->time_tag >= 0 &&
               ((id >> 6) & 0x1) == 0;
    }
    }
    return false;
}

struct Buf {
    long long start = 0;        // first seq slot
    long long span_id = -1;
    long long begin = 0;        // ring byte offset
    std::vector<uint8_t> got;   // ntime * nsrc
};

struct Transmit {
    int fmt = FMT_SIMPLE;
    int sockfd = -1;
    long long rate_pps = 0;     // 0 = unlimited
    double next_time = 0.0;
};

struct Capture {
    int fmt = FMT_SIMPLE;
    int sockfd = -1;
    void* ring = nullptr;
    int nsrc = 1;
    int src0 = 0;
    int payload_size = 0;
    int buffer_ntime = 0;
    int slot_ntime = 0;
    int timeout_ms = 200;
    int batch = 128;
    int decimation = 1;        // TBN seq derivation (stream parameter)

    bft_header_cb header_cb = nullptr;
    void* cb_user = nullptr;

    bool writing = false;
    void* seq = nullptr;
    long long seq0 = -1;
    std::vector<Buf> bufs;      // sliding window, oldest first (max 2)

    long long ngood_bytes = 0;
    long long nmissing_bytes = 0;
    long long ninvalid = 0;
    long long nignored = 0;
    std::vector<long long> src_ngood;

    // recvmmsg state
    std::vector<uint8_t> rxbuf;
    std::vector<mmsghdr> hdrs;
    std::vector<iovec> iovs;

    long long span_nbyte() const {
        return (long long)buffer_ntime * nsrc * payload_size;
    }
};

static uint8_t* span_ptr(Capture* c, long long begin, long long nbyte) {
    unsigned char* base = nullptr;
    long long size = 0, ghost = 0, nrl = 0;
    if (bft_ring_geometry(c->ring, &base, &size, &ghost, &nrl) != BFT_OK
        || !base || size <= 0)
        return nullptr;
    (void)nbyte;
    return base + (begin % size);
}

static int open_buf(Capture* c, long long start) {
    Buf b;
    b.start = start;
    if (bft_ring_reserve(c->ring, c->span_nbyte(), 0, &b.begin,
                         &b.span_id) != BFT_OK)
        return BFT_ERR_STATE;
    uint8_t* p = span_ptr(c, b.begin, c->span_nbyte());
    if (!p) return BFT_ERR_STATE;
    std::memset(p, 0, (size_t)c->span_nbyte());
    b.got.assign((size_t)c->buffer_ntime * c->nsrc, 0);
    c->bufs.push_back(std::move(b));
    return BFT_OK;
}

static void commit_oldest(Capture* c) {
    Buf& b = c->bufs.front();
    uint8_t* p = span_ptr(c, b.begin, c->span_nbyte());
    // per-source loss accounting + >50%-loss blanking
    // (reference: packet_capture.hpp:505-534)
    for (int s = 0; s < c->nsrc; ++s) {
        long long good = 0;
        for (int t = 0; t < c->buffer_ntime; ++t)
            good += b.got[(size_t)t * c->nsrc + s];
        c->src_ngood[s] += good * c->payload_size;
        c->ngood_bytes += good * c->payload_size;
        c->nmissing_bytes +=
            (long long)(c->buffer_ntime - good) * c->payload_size;
        if (good * 2 < c->buffer_ntime && p) {
            for (int t = 0; t < c->buffer_ntime; ++t)
                std::memset(p + ((size_t)t * c->nsrc + s) *
                                    c->payload_size,
                            0, (size_t)c->payload_size);
        }
    }
    bft_ring_commit(c->ring, b.span_id, c->span_nbyte());
    c->bufs.erase(c->bufs.begin());
}

static int begin_sequence(Capture* c, const bft_pkt_desc* d) {
    if (!c->writing) {
        bft_ring_begin_writing(c->ring);
        c->writing = true;
    }
    long long time_tag = 0;
    char hdr[65536];
    char name[256];
    hdr[0] = 0;
    // the callback sees src rebased by src0, like the Python engine
    bft_pkt_desc dd = *d;
    dd.src -= c->src0;
    std::snprintf(name, sizeof(name), "capture-%lld", d->seq);
    if (c->header_cb) {
        if (c->header_cb(c->cb_user, &dd, &time_tag, name,
                         (int)sizeof(name), hdr, (int)sizeof(hdr)) != 0)
            return BFT_ERR_STATE;
    }
    if (bft_ring_begin_sequence(c->ring, name, time_tag, hdr,
                                (long long)std::strlen(hdr), 1,
                                &c->seq) != BFT_OK)
        return BFT_ERR_STATE;
    c->seq0 = (d->seq / c->slot_ntime) * c->slot_ntime;
    c->bufs.clear();
    return BFT_OK;
}

// process one decoded packet; returns true if a span was committed
static bool process_packet(Capture* c, const bft_pkt_desc* d,
                           const uint8_t* payload, int plen,
                           bool* started) {
    bool committed = false;
    int src = d->src - c->src0;
    if (src < 0 || src >= c->nsrc) {
        ++c->nignored;
        return false;
    }
    if (c->seq0 < 0) {
        if (begin_sequence(c, d) != BFT_OK) return false;
        *started = true;
    }
    long long off = d->seq - c->seq0;
    if (off < 0) {
        ++c->nignored;
        return false;
    }
    for (;;) {
        long long last_end = c->bufs.empty()
            ? 0 : c->bufs.back().start + c->buffer_ntime;
        if (off < last_end) break;
        if (c->bufs.size() == 2) {
            commit_oldest(c);
            committed = true;
        }
        if (open_buf(c, last_end) != BFT_OK) return committed;
    }
    for (auto& b : c->bufs) {
        if (b.start <= off && off < b.start + c->buffer_ntime) {
            long long t = off - b.start;
            uint8_t* p = span_ptr(c, b.begin, c->span_nbyte());
            if (p) {
                int n = plen < c->payload_size ? plen : c->payload_size;
                std::memcpy(p + ((size_t)t * c->nsrc + src) *
                                    c->payload_size,
                            payload, (size_t)n);
                b.got[(size_t)t * c->nsrc + src] = 1;
            }
            break;
        } else if (off < b.start) {
            ++c->nignored;   // too late
            break;
        }
    }
    return committed;
}

}  // namespace

extern "C" {

int bft_capture_create(void** out, int fmt, int sockfd, void* ring,
                       int nsrc, int src0, int payload_size,
                       int buffer_ntime, int slot_ntime) {
    if (!out || !ring || nsrc <= 0 || payload_size <= 0 ||
        buffer_ntime <= 0 || slot_ntime <= 0)
        return BFT_ERR_INVALID;
    if (fmt < FMT_SIMPLE || fmt > FMT_DRX8) return BFT_ERR_INVALID;
    auto* c = new Capture();
    c->fmt = fmt;
    c->sockfd = sockfd;
    c->ring = ring;
    c->nsrc = nsrc;
    c->src0 = src0;
    c->payload_size = payload_size;
    c->buffer_ntime = buffer_ntime;
    c->slot_ntime = slot_ntime;
    c->src_ngood.assign(nsrc, 0);
    // size the ring for the span gulps (writer side owns geometry)
    bft_ring_resize(ring, c->span_nbyte(), 4 * c->span_nbyte(), 1);
    int pkt_cap = payload_size + 1024;
    c->rxbuf.assign((size_t)c->batch * pkt_cap, 0);
    c->hdrs.assign(c->batch, mmsghdr());
    c->iovs.assign(c->batch, iovec());
    for (int i = 0; i < c->batch; ++i) {
        c->iovs[i].iov_base = c->rxbuf.data() + (size_t)i * pkt_cap;
        c->iovs[i].iov_len = pkt_cap;
        std::memset(&c->hdrs[i], 0, sizeof(mmsghdr));
        c->hdrs[i].msg_hdr.msg_iov = &c->iovs[i];
        c->hdrs[i].msg_hdr.msg_iovlen = 1;
    }
    *out = c;
    return BFT_OK;
}

int bft_capture_set_header_callback(void* cap, bft_header_cb fn,
                                    void* user) {
    auto* c = static_cast<Capture*>(cap);
    if (!c) return BFT_ERR_INVALID;
    c->header_cb = fn;
    c->cb_user = user;
    return BFT_OK;
}

int bft_capture_set_decimation(void* cap, int decim) {
    auto* c = static_cast<Capture*>(cap);
    if (!c || decim <= 0) return BFT_ERR_INVALID;
    c->decimation = decim;
    return BFT_OK;
}

int bft_capture_set_timeout_ms(void* cap, int ms) {
    auto* c = static_cast<Capture*>(cap);
    if (!c) return BFT_ERR_INVALID;
    c->timeout_ms = ms;
    return BFT_OK;
}

// Run until one span commits (or timeout).  *status_out gets a
// CAPTURE_* code like the Python engine's recv().
int bft_capture_recv(void* cap, int* status_out) {
    auto* c = static_cast<Capture*>(cap);
    if (!c || !status_out) return BFT_ERR_INVALID;
    bool started = false;
    bool committed = false;
    int pkt_cap = c->payload_size + 1024;
    while (!committed) {
        struct pollfd pfd = {c->sockfd, POLLIN, 0};
        int pr = poll(&pfd, 1, c->timeout_ms);   // -1 = block forever
        if (pr <= 0) {
            *status_out = (c->seq0 < 0) ? CAPTURE_NO_DATA
                                        : CAPTURE_INTERRUPTED;
            return BFT_OK;
        }
        int n = recvmmsg(c->sockfd, c->hdrs.data(), c->batch,
                         MSG_DONTWAIT, nullptr);
        if (n < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK ||
                errno == EINTR)
                continue;
            return BFT_ERR_STATE;
        }
        for (int i = 0; i < n; ++i) {
            const uint8_t* pkt =
                c->rxbuf.data() + (size_t)i * pkt_cap;
            int len = (int)c->hdrs[i].msg_len;
            bft_pkt_desc d;
            const uint8_t* payload = nullptr;
            int plen = 0;
            if (!decode_packet(c->fmt, pkt, len, &d, &payload, &plen,
                               c->decimation)) {
                ++c->ninvalid;
                continue;
            }
            committed |= process_packet(c, &d, payload, plen, &started);
        }
    }
    *status_out = started ? CAPTURE_STARTED : CAPTURE_CONTINUED;
    return BFT_OK;
}

int bft_capture_flush(void* cap) {
    auto* c = static_cast<Capture*>(cap);
    if (!c) return BFT_ERR_INVALID;
    while (!c->bufs.empty()) commit_oldest(c);
    return BFT_OK;
}

int bft_capture_end(void* cap) {
    auto* c = static_cast<Capture*>(cap);
    if (!c) return BFT_ERR_INVALID;
    bft_capture_flush(c);
    if (c->seq) {
        bft_ring_end_sequence(c->ring, c->seq);
        c->seq = nullptr;
    }
    if (c->writing) {
        bft_ring_end_writing(c->ring);
        c->writing = false;
    }
    c->seq0 = -1;
    return BFT_OK;
}

int bft_capture_stats(void* cap, long long* ngood, long long* nmissing,
                      long long* ninvalid, long long* nignored) {
    auto* c = static_cast<Capture*>(cap);
    if (!c) return BFT_ERR_INVALID;
    if (ngood) *ngood = c->ngood_bytes;
    if (nmissing) *nmissing = c->nmissing_bytes;
    if (ninvalid) *ninvalid = c->ninvalid;
    if (nignored) *nignored = c->nignored;
    return BFT_OK;
}

int bft_capture_src_ngood(void* cap, long long* out, int n) {
    auto* c = static_cast<Capture*>(cap);
    if (!c || !out) return BFT_ERR_INVALID;
    for (int i = 0; i < n && i < (int)c->src_ngood.size(); ++i)
        out[i] = c->src_ngood[i];
    return BFT_OK;
}

int bft_capture_destroy(void* cap) {
    auto* c = static_cast<Capture*>(cap);
    delete c;
    return BFT_OK;
}

// ---------------------------------------------------------------------------
// Native packet writer: header fill + sendmmsg batches
// (reference: src/packet_writer.hpp:59-580 — HeaderInfo + per-format
// fillers + senders + token-bucket rate limiter)
// ---------------------------------------------------------------------------

int bft_transmit_create(void** out, int fmt, int sockfd) {
    if (!out) return BFT_ERR_INVALID;
    if (fmt != FMT_SIMPLE && fmt != FMT_CHIPS) return BFT_ERR_INVALID;
    auto* t = new Transmit();
    t->fmt = fmt;
    t->sockfd = sockfd;
    *out = t;
    return BFT_OK;
}

int bft_transmit_set_rate(void* tr, long long pps) {
    auto* t = static_cast<Transmit*>(tr);
    if (!t) return BFT_ERR_INVALID;
    t->rate_pps = pps;
    t->next_time = 0.0;
    return BFT_OK;
}

// Send nseq*nsrc packets: packet (i, j) carries seq0 + i*seq_inc and
// src0 + j*src_inc with payload data[i, j, :payload_size].
int bft_transmit_send(void* tr, long long seq0, long long seq_inc,
                      int src0, int src_inc, int hdr_nsrc, int chan0,
                      int nchan, int tuning, int gain,
                      const unsigned char* data, int nseq, int nsrc,
                      int payload_size, long long* nsent_out) {
    auto* t = static_cast<Transmit*>(tr);
    if (!t || !data || nseq <= 0 || nsrc <= 0 || payload_size <= 0)
        return BFT_ERR_INVALID;
    const int hdr_len = (t->fmt == FMT_SIMPLE) ? 8 : 16;
    const int pkt_len = hdr_len + payload_size;
    const int BATCH = 64;
    std::vector<uint8_t> bufs((size_t)BATCH * pkt_len);
    std::vector<mmsghdr> hdrs(BATCH);
    std::vector<iovec> iovs(BATCH);
    for (int k = 0; k < BATCH; ++k) {
        iovs[k].iov_base = bufs.data() + (size_t)k * pkt_len;
        iovs[k].iov_len = pkt_len;
        std::memset(&hdrs[k], 0, sizeof(mmsghdr));
        hdrs[k].msg_hdr.msg_iov = &iovs[k];
        hdrs[k].msg_hdr.msg_iovlen = 1;
    }
    long long nsent = 0;
    int k = 0;
    auto flush = [&]() -> bool {
        int off = 0;
        while (off < k) {
            int n = sendmmsg(t->sockfd, hdrs.data() + off, k - off, 0);
            if (n < 0) {
                if (errno == EINTR) continue;
                if (errno == EAGAIN || errno == EWOULDBLOCK ||
                    errno == ENOBUFS) {
                    // wait for buffer space, then retry
                    struct pollfd pfd = {t->sockfd, POLLOUT, 0};
                    poll(&pfd, 1, 100);
                    continue;
                }
                return false;
            }
            nsent += n;
            off += n;
            if (t->rate_pps > 0 && n > 0) {
                // token bucket charged by packets ACTUALLY sent
                timespec ts;
                clock_gettime(CLOCK_MONOTONIC, &ts);
                double now = ts.tv_sec + ts.tv_nsec * 1e-9;
                if (t->next_time <= 0.0) t->next_time = now;
                t->next_time += (double)n / (double)t->rate_pps;
                double delay = t->next_time - now;
                if (delay > 0) {
                    timespec d;
                    d.tv_sec = (time_t)delay;
                    d.tv_nsec = (long)((delay - (time_t)delay) * 1e9);
                    nanosleep(&d, nullptr);
                }
            }
        }
        k = 0;
        return true;
    };
    for (int i = 0; i < nseq; ++i) {
        for (int j = 0; j < nsrc; ++j) {
            uint8_t* p = bufs.data() + (size_t)k * pkt_len;
            long long seq = seq0 + i * seq_inc;
            int src = src0 + j * src_inc;
            if (t->fmt == FMT_SIMPLE) {
                wbe64(p, (uint64_t)seq);
            } else {  // FMT_CHIPS: mirror CHIPSHeaderFiller
                p[0] = (uint8_t)(src + 1);
                p[1] = (uint8_t)tuning;
                p[2] = (uint8_t)nchan;
                p[3] = 1;
                p[4] = 0;
                p[5] = (uint8_t)hdr_nsrc;
                wbe16(p + 6, (uint16_t)chan0);
                wbe64(p + 8, (uint64_t)seq);
            }
            std::memcpy(p + hdr_len,
                        data + ((size_t)i * nsrc + j) * payload_size,
                        (size_t)payload_size);
            if (++k == BATCH && !flush()) {
                if (nsent_out) *nsent_out = nsent;
                return BFT_ERR_STATE;
            }
        }
    }
    if (k && !flush()) {
        if (nsent_out) *nsent_out = nsent;
        return BFT_ERR_STATE;
    }
    (void)gain;
    if (nsent_out) *nsent_out = nsent;
    return BFT_OK;
}

int bft_transmit_destroy(void* tr) {
    delete static_cast<Transmit*>(tr);
    return BFT_OK;
}

}  // extern "C"

#else  // !BFT_HAVE_CAPTURE: portable stubs so the .so builds anywhere

extern "C" {
int bft_capture_create(void**, int, int, void*, int, int, int, int,
                       int) { return BFT_ERR_INVALID; }
int bft_capture_set_header_callback(void*, bft_header_cb, void*) {
    return BFT_ERR_INVALID;
}
int bft_capture_set_timeout_ms(void*, int) { return BFT_ERR_INVALID; }
int bft_capture_set_decimation(void*, int) { return BFT_ERR_INVALID; }
int bft_capture_recv(void*, int*) { return BFT_ERR_INVALID; }
int bft_capture_flush(void*) { return BFT_ERR_INVALID; }
int bft_capture_end(void*) { return BFT_ERR_INVALID; }
int bft_capture_stats(void*, long long*, long long*, long long*,
                      long long*) { return BFT_ERR_INVALID; }
int bft_capture_src_ngood(void*, long long*, int) {
    return BFT_ERR_INVALID;
}
int bft_capture_destroy(void*) { return BFT_OK; }
int bft_transmit_create(void**, int, int) { return BFT_ERR_INVALID; }
int bft_transmit_set_rate(void*, long long) { return BFT_ERR_INVALID; }
int bft_transmit_send(void*, long long, long long, int, int, int, int,
                      int, int, int, const unsigned char*, int, int,
                      int, long long*) { return BFT_ERR_INVALID; }
int bft_transmit_destroy(void*) { return BFT_OK; }
}  // extern "C"

#endif  // BFT_HAVE_CAPTURE
