// Native ring buffer runtime for bifrost_tpu.
//
// Re-implements the semantics of the reference ring
// (reference: src/ring_impl.{hpp,cpp} — ghost region, guarantees,
// tail-pull overwrite, in-order commit barrier, blocking acquire with
// partial final span, live resize preserving buffered data) as a small
// C++17 library with a pure-C ABI consumed from Python via ctypes
// (replacing the reference's ctypesgen-generated bindings,
// python/Makefile.in:23-30).
//
// Concurrency model matches the reference: one mutex per ring plus
// condition variables for readers (data committed), writers (space
// freed), sequences (new sequence / sequence ended), and span-close
// (resize waits for quiescence).
//
// Memory spaces: this core manages HOST memory (posix_memalign, 512-byte
// aligned like BF_ALIGNMENT, reference: src/memory.cpp:334-351).  Device
// ('tpu') rings keep their payloads as jax Arrays on the Python side;
// only host rings route here.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#if defined(__linux__)
#include <dirent.h>
#include <sys/syscall.h>
#include <unistd.h>
#endif
#include <deque>
#include <limits>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <vector>

#define BFT_OK 0
#define BFT_END_OF_DATA 1
#define BFT_WOULD_BLOCK 2
#define BFT_ERR_INVALID (-1)
#define BFT_ERR_STATE (-2)
#define BFT_ERR_ALLOC (-3)

namespace {

constexpr int64_t ALIGNMENT = 512;
constexpr int64_t NO_END = std::numeric_limits<int64_t>::max();

struct Sequence {
    std::string name;
    long long time_tag = -1;
    std::string header;
    int64_t begin = 0;
    int64_t end = NO_END;     // NO_END while open
    int64_t nringlet = 1;
    Sequence* next = nullptr;

    bool finished() const { return end != NO_END; }
};

struct WSpan {
    int64_t id = 0;
    int64_t begin = 0;
    int64_t nbyte = 0;
    int64_t commit_nbyte = -1;   // -1 = still open
};

struct Reader {
    int64_t id = 0;
    bool guarantee = true;
    int64_t guarantee_offset = 0;   // only meaningful if guarantee
    // Begin offsets of this reader's OPEN spans.  A guaranteed reader
    // with several spans outstanding (the bridge's credit window holds
    // spans un-released until the peer acks them) must keep the
    // guarantee at the OLDEST open span — the reference refcount-locks
    // the tail per span (ring_impl.hpp:110-141); a bare watermark
    // would let a later acquire unlock bytes an earlier open span is
    // still exporting zero-copy.
    std::multiset<int64_t> open_spans;
    // END offset per open-span begin (max over duplicates): a release
    // advances the consumed frontier to the span's END — the reader
    // READ those bytes, so a drop_oldest shed racing the
    // no-open-spans window must not count them again (the shed ledger
    // would otherwise exceed produced == delivered + shed).
    std::map<int64_t, int64_t> open_span_ends;
    // Highest span END ever RELEASED: out-of-order releases must
    // advance the guarantee to this high-water mark once no span is
    // open, not to the last-released begin.
    int64_t release_high = 0;
};

// Bind freshly allocated ring pages to the NUMA node of `core` via the
// raw mbind syscall (reference binds ring memory with hwloc:
// ring_impl.cpp:164-166).  Advisory: failures are ignored.
#if defined(__linux__)
static void numa_bind_to_core(void* addr, size_t len, int core) {
#ifdef SYS_mbind
    if (core < 0 || !addr || !len) return;
    char path[96];
    std::snprintf(path, sizeof(path),
                  "/sys/devices/system/cpu/cpu%d", core);
    DIR* d = opendir(path);
    if (!d) return;
    int node = -1;
    while (struct dirent* e = readdir(d)) {
        if (std::strncmp(e->d_name, "node", 4) == 0 &&
            e->d_name[4] >= '0' && e->d_name[4] <= '9') {
            node = std::atoi(e->d_name + 4);
            break;
        }
    }
    closedir(d);
    if (node < 0) return;
    const int MPOL_BIND_ = 2;
    unsigned long mask = 1UL << node;
    long page = sysconf(_SC_PAGESIZE);
    uintptr_t start = (uintptr_t)addr & ~(uintptr_t)(page - 1);
    size_t length = len + ((uintptr_t)addr - start);
    syscall(SYS_mbind, (void*)start, length, MPOL_BIND_, &mask,
            8 * sizeof(mask) + 1, 0);
#else
    (void)addr; (void)len; (void)core;
#endif
}
#else
static void numa_bind_to_core(void*, size_t, int) {}
#endif

struct Ring {
    std::mutex mtx;
    std::condition_variable read_cv;     // data committed / seq ended
    std::condition_variable write_cv;    // space freed
    std::condition_variable seq_cv;      // sequence list changed
    std::condition_variable span_cv;     // span closed (resize gate)

    std::string name;

    uint8_t* buf = nullptr;
    int64_t size = 0;        // per-lane capacity
    int64_t ghost = 0;       // per-lane ghost span
    int64_t nringlet = 1;

    int64_t tail = 0;
    int64_t head = 0;
    int64_t reserve_head = 0;

    // Sequences are kept for the lifetime of the ring (registry) but the
    // *live* window is [live_begin, end of deque).
    std::deque<std::unique_ptr<Sequence>> sequences;
    size_t live_begin = 0;

    std::deque<WSpan> open_wspans;       // reserve order
    int64_t next_wspan_id = 1;

    std::map<int64_t, std::unique_ptr<Reader>> readers;
    int64_t next_reader_id = 1;

    int nwrite_open = 0;
    int nread_open = 0;
    bool writing = false;
    bool eod = false;
    int bind_core = -1;      // NUMA-bind new allocations to this core
    std::atomic<long long> total_written{0};

    // deferred resize (bft_ring_request_resize): target geometry
    // recorded while spans were open, applied by the span-release
    // paths the moment the ring goes quiescent.  -1 = none pending.
    int64_t pending_ghost = -1;
    int64_t pending_size = -1;
    int64_t pending_nringlet = -1;
    // external apply blockers (bft_ring_resize_hold): the Python layer
    // holds one per registered deferred D2H fill, whose cached numpy
    // view into THIS buffer would dangle under a re-layout
    int resize_holds = 0;

    int64_t lane_nbyte() const { return size + ghost; }

    bool resize_pending_locked() const { return pending_size >= 0; }

    // fold the pending request into an explicit target (MAX semantics)
    // and clear it; callers apply the returned geometry themselves.
    // MUST NOT be called while resize_holds > 0: the holds exist
    // precisely because a deferred fill's cached view into the
    // current buffer would dangle under a re-layout — callers that
    // reach quiescence on spans alone keep the target pending.
    void fold_pending_locked(int64_t* g, int64_t* s, int64_t* n) {
        if (resize_holds != 0) return;
        if (pending_ghost > *g) *g = pending_ghost;
        if (pending_size > *s) *s = pending_size;
        if (pending_nringlet > *n) *n = pending_nringlet;
        pending_ghost = pending_size = pending_nringlet = -1;
    }

    // apply a pending deferred resize if quiescent RIGHT NOW; returns
    // BFT_OK whether or not anything was pending (alloc errors pass
    // through)
    int maybe_apply_pending_locked() {
        if (!resize_pending_locked()) return BFT_OK;
        if (nwrite_open != 0 || nread_open != 0 || resize_holds != 0)
            return BFT_OK;
        int64_t g = ghost, s = size, n = nringlet;
        fold_pending_locked(&g, &s, &n);
        if (g == ghost && s == size && n == nringlet) return BFT_OK;
        int rc = realloc_locked(s, g, n);
        if (rc != BFT_OK) {
            // fold cleared the pending target; an allocation failure
            // must not silently lose the requested grow (the tuner's
            // re-issue contract relies on the target staying pending
            // until it lands) — restore it for the next quiescence
            if (g > ghost && g > pending_ghost) pending_ghost = g;
            if (s > size && s > pending_size) pending_size = s;
            if (n > nringlet && n > pending_nringlet)
                pending_nringlet = n;
            return rc;
        }
        write_cv.notify_all();
        read_cv.notify_all();
        return BFT_OK;
    }

    int64_t min_guarantee_locked() const {
        int64_t g = NO_END;
        for (auto& kv : readers) {
            if (kv.second->guarantee && kv.second->guarantee_offset < g)
                g = kv.second->guarantee_offset;
        }
        return g;
    }

    void gc_sequences_locked() {
        // drop fully-consumed finished sequences from the live window;
        // the Sequence objects themselves stay valid (Python may hold
        // pointers) but their header payloads are released
        while (sequences.size() - live_begin > 1) {
            Sequence* s = sequences[live_begin].get();
            if (s->finished() && s->end <= tail && s->next != nullptr) {
                std::string().swap(s->header);
                ++live_begin;
            } else {
                break;
            }
        }
    }

    int realloc_locked(int64_t new_size, int64_t new_ghost,
                       int64_t new_nringlet) {
        uint8_t* nb = nullptr;
        size_t total = (size_t)new_nringlet * (new_size + new_ghost);
        if (posix_memalign(reinterpret_cast<void**>(&nb), ALIGNMENT,
                           total ? total : ALIGNMENT) != 0)
            return BFT_ERR_ALLOC;
        // bind BEFORE first touch: mbind without MPOL_MF_MOVE only
        // steers future page faults, and memset faults every page
        numa_bind_to_core(nb, total, bind_core);
        std::memset(nb, 0, total);
        if (buf && head > tail) {
            // preserve [tail, head) across the re-layout, per lane
            int64_t t = tail, h = head;
            if (h - t > new_size) t = h - new_size;
            for (int64_t o = t; o < h;) {
                int64_t run = h - o;
                run = std::min(run, size - (o % size));
                run = std::min(run, new_size - (o % new_size));
                for (int64_t lane = 0;
                     lane < std::min(nringlet, new_nringlet); ++lane) {
                    std::memcpy(nb + lane * (new_size + new_ghost)
                                   + (o % new_size),
                                buf + lane * lane_nbyte() + (o % size),
                                (size_t)run);
                }
                o += run;
            }
        }
        std::free(buf);
        buf = nb;
        size = new_size;
        ghost = new_ghost;
        nringlet = new_nringlet;
        return BFT_OK;
    }

    void ghost_write_locked(int64_t begin, int64_t nbyte) {
        // mirror overflow past the nominal end back to the start
        int64_t bo = begin % size;
        int64_t over = bo + nbyte - size;
        if (over > 0) {
            for (int64_t lane = 0; lane < nringlet; ++lane) {
                uint8_t* base = buf + lane * lane_nbyte();
                std::memcpy(base, base + size, (size_t)over);
            }
        }
    }

    void ghost_read_locked(int64_t begin, int64_t nbyte) {
        // refresh the ghost from the start before a wrapped read
        int64_t bo = begin % size;
        int64_t over = bo + nbyte - size;
        if (over > 0) {
            for (int64_t lane = 0; lane < nringlet; ++lane) {
                uint8_t* base = buf + lane * lane_nbyte();
                std::memcpy(base + size, base, (size_t)over);
            }
        }
    }

    ~Ring() { std::free(buf); }
};

}  // namespace

extern "C" {

int bft_ring_create(void** out, const char* name) {
    if (!out) return BFT_ERR_INVALID;
    Ring* r = new (std::nothrow) Ring();
    if (!r) return BFT_ERR_ALLOC;
    r->name = name ? name : "";
    *out = r;
    return BFT_OK;
}

int bft_ring_destroy(void* ring) {
    delete static_cast<Ring*>(ring);
    return BFT_OK;
}

int bft_ring_set_core(void* ring_, int core) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    r->bind_core = core;
    return BFT_OK;
}

int bft_ring_resize(void* ring_, long long contig, long long total,
                    long long nringlet) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r) return BFT_ERR_INVALID;
    std::unique_lock<std::mutex> lk(r->mtx);
    if (total < 0) total = contig * 4;
    int64_t ghost = std::max<int64_t>(r->ghost, contig);
    int64_t size = std::max<int64_t>(r->size, total);
    int64_t nrl = std::max<int64_t>(r->nringlet, nringlet);
    // fold in any deferred request_resize target: this blocking path
    // reaches quiescence anyway, so the pending geometry lands here
    if (r->resize_pending_locked())
        r->fold_pending_locked(&ghost, &size, &nrl);
    if (size == r->size && ghost == r->ghost && nrl == r->nringlet)
        return BFT_OK;
    // wait for quiescence (reference: RingReallocLock)
    r->span_cv.wait(lk, [&] {
        return r->nwrite_open == 0 && r->nread_open == 0;
    });
    int rc = r->realloc_locked(size, ghost, nrl);
    if (rc != BFT_OK) return rc;
    r->write_cv.notify_all();
    r->read_cv.notify_all();
    return BFT_OK;
}

int bft_ring_request_resize(void* ring_, long long contig,
                            long long total, long long nringlet,
                            int* applied) {
    // Non-blocking deferred resize (the auto-tuner's retune protocol):
    // apply immediately when quiescent, else record the target and let
    // bft_ring_commit / bft_reader_release apply it the moment the
    // oldest open span releases and no other span remains open.
    // *applied = 1 when the requested geometry is live on return.
    Ring* r = static_cast<Ring*>(ring_);
    if (!r || !applied) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    if (total < 0) total = contig * 4;
    int64_t ghost = std::max<int64_t>(r->ghost, contig);
    int64_t size = std::max<int64_t>(r->size, total);
    int64_t nrl = std::max<int64_t>(r->nringlet, nringlet);
    if (size == r->size && ghost == r->ghost && nrl == r->nringlet) {
        *applied = 1;                 // no-op: already that large
        return BFT_OK;
    }
    if (ghost > r->pending_ghost) r->pending_ghost = ghost;
    if (size > r->pending_size) r->pending_size = size;
    if (nrl > r->pending_nringlet) r->pending_nringlet = nrl;
    int rc = r->maybe_apply_pending_locked();
    if (rc != BFT_OK) return rc;
    *applied = r->resize_pending_locked() ? 0 : 1;
    return BFT_OK;
}

int bft_ring_resize_hold(void* ring_, int delta) {
    // adjust the external apply-blocker count (deferred fills); a drop
    // to zero is itself a quiescence point
    Ring* r = static_cast<Ring*>(ring_);
    if (!r) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    r->resize_holds += delta;
    if (r->resize_holds < 0) r->resize_holds = 0;
    if (r->resize_holds == 0) return r->maybe_apply_pending_locked();
    return BFT_OK;
}

int bft_ring_resize_pending(void* ring_, int* pending) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r || !pending) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    *pending = r->resize_pending_locked() ? 1 : 0;
    return BFT_OK;
}

int bft_ring_geometry(void* ring_, unsigned char** buf, long long* size,
                      long long* ghost, long long* nringlet) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    if (buf) *buf = r->buf;
    if (size) *size = r->size;
    if (ghost) *ghost = r->ghost;
    if (nringlet) *nringlet = r->nringlet;
    return BFT_OK;
}

int bft_ring_begin_writing(void* ring_) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    r->writing = true;
    r->eod = false;
    return BFT_OK;
}

int bft_ring_end_writing(void* ring_) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    r->writing = false;
    r->eod = true;
    r->read_cv.notify_all();
    r->seq_cv.notify_all();
    return BFT_OK;
}

int bft_ring_begin_sequence(void* ring_, const char* name,
                            long long time_tag, const char* header,
                            long long header_len, long long nringlet,
                            void** seq_out) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r || !seq_out) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    if (!r->sequences.empty()) {
        Sequence* prev = r->sequences.back().get();
        if (!prev->finished()) return BFT_ERR_STATE;
    }
    auto seq = std::make_unique<Sequence>();
    seq->name = name ? name : "";
    seq->time_tag = time_tag;
    seq->header.assign(header ? header : "", (size_t)header_len);
    seq->begin = r->head;
    seq->nringlet = nringlet;
    Sequence* sp = seq.get();
    if (!r->sequences.empty())
        r->sequences.back()->next = sp;
    r->sequences.push_back(std::move(seq));
    r->seq_cv.notify_all();
    *seq_out = sp;
    return BFT_OK;
}

int bft_ring_end_sequence(void* ring_, void* seq_) {
    Ring* r = static_cast<Ring*>(ring_);
    Sequence* s = static_cast<Sequence*>(seq_);
    if (!r || !s) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    s->end = r->head;
    r->read_cv.notify_all();
    r->seq_cv.notify_all();
    return BFT_OK;
}

int bft_seq_info(void* seq_, const char** name, long long* time_tag,
                 const char** header, long long* header_len,
                 long long* begin, long long* nringlet) {
    Sequence* s = static_cast<Sequence*>(seq_);
    if (!s) return BFT_ERR_INVALID;
    if (name) *name = s->name.c_str();
    if (time_tag) *time_tag = s->time_tag;
    if (header) *header = s->header.data();
    if (header_len) *header_len = (long long)s->header.size();
    if (begin) *begin = s->begin;
    if (nringlet) *nringlet = s->nringlet;
    return BFT_OK;
}

int bft_seq_end_offset(void* seq_, long long* end) {
    Sequence* s = static_cast<Sequence*>(seq_);
    if (!s || !end) return BFT_ERR_INVALID;
    *end = s->finished() ? s->end : -1;
    return BFT_OK;
}

// ---- writer spans ---------------------------------------------------------

int bft_ring_reserve(void* ring_, long long nbyte, int nonblocking,
                     long long* begin_out, long long* span_id_out) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r || !begin_out || !span_id_out || nbyte < 0)
        return BFT_ERR_INVALID;
    std::unique_lock<std::mutex> lk(r->mtx);
    // A queued partial commit truncates reserve_head when it lands;
    // reserving past it would hand out offsets that the truncation
    // then invalidates.
    for (auto& ws : r->open_wspans)
        if (ws.commit_nbyte >= 0 && ws.commit_nbyte < ws.nbyte)
            return BFT_ERR_STATE;
    if (nbyte > r->ghost) {
        // guaranteed-contiguous window too small; grow it (folding in
        // any deferred request_resize target — we are at quiescence)
        r->span_cv.wait(lk, [&] {
            return r->nwrite_open == 0 && r->nread_open == 0;
        });
        int64_t g = std::max<int64_t>(r->ghost, nbyte);
        int64_t s = std::max<int64_t>(r->size, nbyte * 4);
        int64_t n = r->nringlet;
        if (r->resize_pending_locked())
            r->fold_pending_locked(&g, &s, &n);
        int rc = r->realloc_locked(s, g, n);
        if (rc != BFT_OK) return rc;
    }
    int64_t begin = r->reserve_head;
    int64_t new_reserve = begin + nbyte;
    for (;;) {
        int64_t new_tail = new_reserve - r->size;
        int64_t limit = std::min<int64_t>(r->head,
                                          r->min_guarantee_locked());
        if (new_tail <= limit) break;
        if (nonblocking) return BFT_WOULD_BLOCK;
        r->write_cv.wait(lk);
    }
    r->reserve_head = new_reserve;
    int64_t new_tail = new_reserve - r->size;
    if (new_tail > r->tail) {
        r->tail = new_tail;     // overwrite: pull the tail forward
        r->gc_sequences_locked();
    }
    WSpan ws;
    ws.id = r->next_wspan_id++;
    ws.begin = begin;
    ws.nbyte = nbyte;
    r->open_wspans.push_back(ws);
    r->nwrite_open += 1;
    *begin_out = begin;
    *span_id_out = ws.id;
    return BFT_OK;
}

int bft_ring_reserve_shed(void* ring_, long long nbyte,
                          long long frame_nbyte, long long* begin_out,
                          long long* span_id_out,
                          long long* shed_bytes_out) {
    // bft_ring_reserve with the drop_oldest overload policy
    // (docs/robustness.md "Overload & degradation"): instead of
    // blocking on guaranteed readers, advance their guarantees in
    // whole-frame steps past the bytes this reservation must
    // overwrite — clamped at each reader's oldest OPEN span, so a
    // held span's zero-copy view is never invalidated.  The shed is
    // COUNTED: *shed_bytes_out accumulates the min-guarantee advance
    // (== the bytes a sequential guaranteed reader will observe as
    // nframe_skipped at its next acquire — the byte-accurate audit
    // the chaos harness checks).  Blocks only on the committed head
    // (the writer's own open spans) and on readers pinned by open
    // spans, both of which resolve by peer progress — never a
    // deadlock against a slow reader.
    Ring* r = static_cast<Ring*>(ring_);
    if (!r || !begin_out || !span_id_out || !shed_bytes_out ||
        nbyte < 0)
        return BFT_ERR_INVALID;
    if (frame_nbyte <= 0) frame_nbyte = 1;
    *shed_bytes_out = 0;
    std::unique_lock<std::mutex> lk(r->mtx);
    for (auto& ws : r->open_wspans)
        if (ws.commit_nbyte >= 0 && ws.commit_nbyte < ws.nbyte)
            return BFT_ERR_STATE;
    if (nbyte > r->ghost) {
        r->span_cv.wait(lk, [&] {
            return r->nwrite_open == 0 && r->nread_open == 0;
        });
        int64_t g = std::max<int64_t>(r->ghost, nbyte);
        int64_t s = std::max<int64_t>(r->size, nbyte * 4);
        int64_t n = r->nringlet;
        if (r->resize_pending_locked())
            r->fold_pending_locked(&g, &s, &n);
        int rc = r->realloc_locked(s, g, n);
        if (rc != BFT_OK) return rc;
    }
    int64_t begin = r->reserve_head;
    int64_t new_reserve = begin + nbyte;
    for (;;) {
        int64_t new_tail = new_reserve - r->size;
        int64_t limit = std::min<int64_t>(r->head,
                                          r->min_guarantee_locked());
        if (new_tail <= limit) break;
        // shed: only guaranteed readers can be advanced, and only
        // over COMMITTED bytes (new_tail <= head); otherwise the
        // writer is blocked on its own commit barrier and must wait
        bool advanced = false;
        if (new_tail <= r->head) {
            int64_t old_min = r->min_guarantee_locked();
            for (auto& kv : r->readers) {
                Reader* rd = kv.second.get();
                if (!rd->guarantee || rd->guarantee_offset >= new_tail)
                    continue;
                int64_t target = rd->guarantee_offset +
                    ((new_tail - rd->guarantee_offset + frame_nbyte - 1)
                     / frame_nbyte) * frame_nbyte;
                if (!rd->open_spans.empty())
                    target = std::min<int64_t>(
                        target, *rd->open_spans.begin());
                if (target > rd->guarantee_offset) {
                    rd->guarantee_offset = target;
                    advanced = true;
                }
            }
            if (advanced) {
                int64_t new_min = r->min_guarantee_locked();
                if (new_min > old_min && old_min != NO_END)
                    *shed_bytes_out += new_min - old_min;
                continue;           // re-check the limit
            }
        }
        r->write_cv.wait(lk);
    }
    r->reserve_head = new_reserve;
    int64_t new_tail = new_reserve - r->size;
    if (new_tail > r->tail) {
        r->tail = new_tail;
        r->gc_sequences_locked();
    }
    WSpan ws;
    ws.id = r->next_wspan_id++;
    ws.begin = begin;
    ws.nbyte = nbyte;
    r->open_wspans.push_back(ws);
    r->nwrite_open += 1;
    *begin_out = begin;
    *span_id_out = ws.id;
    return BFT_OK;
}

int bft_ring_commit(void* ring_, long long span_id, long long commit_nbyte) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    // A partial commit truncates reserve_head, so it is only legal on
    // the newest outstanding span; reject it up front, before any state
    // changes (an error raised mid-pop used to leak nwrite_open and
    // permanently block resize quiescence).
    bool found = false;
    for (auto& ws : r->open_wspans) {
        if (ws.id == span_id) {
            if (ws.commit_nbyte >= 0) return BFT_ERR_STATE;
            if (commit_nbyte > ws.nbyte) return BFT_ERR_INVALID;
            if (commit_nbyte < ws.nbyte &&
                ws.id != r->open_wspans.back().id)
                return BFT_ERR_STATE;
            ws.commit_nbyte = commit_nbyte;
            found = true;
            break;
        }
    }
    if (!found) return BFT_ERR_INVALID;
    // in-order commit barrier (reference: ring_impl.cpp:591-594)
    while (!r->open_wspans.empty() &&
           r->open_wspans.front().commit_nbyte >= 0) {
        WSpan ws = r->open_wspans.front();
        r->open_wspans.pop_front();
        if (ws.commit_nbyte > 0)
            r->ghost_write_locked(ws.begin, ws.commit_nbyte);
        if (ws.commit_nbyte < ws.nbyte)
            r->reserve_head = ws.begin + ws.commit_nbyte;
        r->head = ws.begin + ws.commit_nbyte;
        r->total_written += ws.commit_nbyte;
        r->nwrite_open -= 1;
    }
    // quiescence point: a deferred request_resize applies the moment
    // no span remains open
    r->maybe_apply_pending_locked();
    r->read_cv.notify_all();
    r->span_cv.notify_all();
    return BFT_OK;
}

// ---- readers --------------------------------------------------------------

int bft_reader_create(void* ring_, int guarantee, long long* reader_id) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r || !reader_id) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    auto rd = std::make_unique<Reader>();
    rd->id = r->next_reader_id++;
    rd->guarantee = guarantee != 0;
    rd->guarantee_offset = r->tail;
    *reader_id = rd->id;
    r->readers[rd->id] = std::move(rd);
    return BFT_OK;
}

int bft_reader_destroy(void* ring_, long long reader_id) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    r->readers.erase(reader_id);
    r->write_cv.notify_all();
    return BFT_OK;
}

int bft_reader_set_guarantee(void* ring_, long long reader_id,
                             long long offset, int clamp_forward_only) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    auto it = r->readers.find(reader_id);
    if (it == r->readers.end()) return BFT_ERR_INVALID;
    Reader* rd = it->second.get();
    // a sequence move (reader_moved) must not unlock bytes a still-open
    // span of the previous sequence is exporting; mode 2 (the poison
    // wakeup) forces past open spans — the ring is dead and blocked
    // writers must be released
    if (clamp_forward_only != 2 && !rd->open_spans.empty())
        offset = std::min<long long>(offset, *rd->open_spans.begin());
    if (clamp_forward_only && offset < rd->guarantee_offset)
        return BFT_OK;
    rd->guarantee_offset = std::max<int64_t>(offset, 0);
    r->write_cv.notify_all();
    return BFT_OK;
}

// which: 0=specific(name), 1=at(time_tag), 2=latest, 3=earliest
int bft_ring_open_sequence(void* ring_, int which, const char* name,
                           long long time_tag, void** seq_out) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r || !seq_out) return BFT_ERR_INVALID;
    std::unique_lock<std::mutex> lk(r->mtx);
    for (;;) {
        for (size_t i = r->live_begin; i < r->sequences.size(); ++i) {
            Sequence* s = r->sequences[i].get();
            switch (which) {
                case 0:
                    if (s->name == (name ? name : "")) {
                        *seq_out = s;
                        return BFT_OK;
                    }
                    break;
                case 1:
                    if (s->time_tag == time_tag) {
                        *seq_out = s;
                        return BFT_OK;
                    }
                    break;
                case 3:
                    if (!s->finished() || s->end > r->tail) {
                        *seq_out = s;
                        return BFT_OK;
                    }
                    break;
                default:
                    break;
            }
        }
        if (which == 2 && r->live_begin < r->sequences.size()) {
            *seq_out = r->sequences.back().get();
            return BFT_OK;
        }
        if (which == 3 && r->live_begin < r->sequences.size()) {
            *seq_out = r->sequences.back().get();
            return BFT_OK;
        }
        if (r->eod) return BFT_END_OF_DATA;
        r->seq_cv.wait(lk);
    }
}

int bft_seq_next(void* ring_, void* seq_, void** next_out) {
    Ring* r = static_cast<Ring*>(ring_);
    Sequence* s = static_cast<Sequence*>(seq_);
    if (!r || !s || !next_out) return BFT_ERR_INVALID;
    std::unique_lock<std::mutex> lk(r->mtx);
    for (;;) {
        if (s->next) {
            *next_out = s->next;
            return BFT_OK;
        }
        if (r->eod && s->finished()) return BFT_END_OF_DATA;
        r->seq_cv.wait(lk);
    }
}

int bft_reader_acquire(void* ring_, long long reader_id, void* seq_,
                       long long offset, long long nbyte,
                       long long frame_nbyte, long long* begin_out,
                       long long* nbyte_out) {
    Ring* r = static_cast<Ring*>(ring_);
    Sequence* s = static_cast<Sequence*>(seq_);
    if (!r || !s || !begin_out || !nbyte_out || frame_nbyte <= 0)
        return BFT_ERR_INVALID;
    std::unique_lock<std::mutex> lk(r->mtx);
    int64_t want_begin = s->begin + offset;
    // NOTE: never cache the Reader* across a cv wait — a concurrent
    // bft_reader_destroy can free it while the mutex is released.
    auto find_reader = [&]() -> Reader* {
        auto it = r->readers.find(reader_id);
        return it == r->readers.end() ? nullptr : it->second.get();
    };
    {
        Reader* rd = find_reader();
        // pre-wait bump: only when no span is open — an open span's
        // begin already bounds the guarantee and must keep doing so
        if (rd && rd->guarantee && rd->open_spans.empty()) {
            int64_t g = std::min<int64_t>(want_begin, r->head);
            if (g > rd->guarantee_offset) rd->guarantee_offset = g;
        }
    }
    int64_t end;
    for (;;) {
        int64_t seq_end = s->finished() ? s->end : NO_END;
        if (seq_end != NO_END && want_begin >= seq_end)
            return BFT_END_OF_DATA;
        int64_t limit = (seq_end != NO_END) ? seq_end
                        : (r->eod ? r->head : NO_END);
        if (r->eod && limit != NO_END && want_begin >= limit)
            return BFT_END_OF_DATA;
        if (want_begin + nbyte <= r->head) {
            end = want_begin + nbyte;
            break;
        }
        if (limit != NO_END && limit <= r->head) {
            end = std::min<int64_t>(limit, want_begin + nbyte);
            break;
        }
        r->read_cv.wait(lk);
    }
    int64_t begin = want_begin;
    if (begin < r->tail) {
        int64_t skip = r->tail - begin;
        skip = ((skip + frame_nbyte - 1) / frame_nbyte) * frame_nbyte;
        begin = std::min<int64_t>(begin + skip, end);
    }
    Reader* rd = find_reader();   // re-lookup: may have been destroyed
    if (rd && rd->guarantee) {
        rd->open_spans.insert(begin);
        int64_t& e = rd->open_span_ends[begin];
        if (end > e) e = end;
        // guarantee = oldest open span (never jumps past a held
        // span); an ADVANCE frees writer space, so notify
        int64_t g = *rd->open_spans.begin();
        if (g > rd->guarantee_offset) r->write_cv.notify_all();
        rd->guarantee_offset = g;
    }
    int64_t got = std::max<int64_t>(end - begin, 0);
    if (got > 0) r->ghost_read_locked(begin, got);
    r->nread_open += 1;
    *begin_out = begin;
    *nbyte_out = got;
    return BFT_OK;
}

int bft_reader_release(void* ring_, long long reader_id,
                       long long span_begin) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    auto it = r->readers.find(reader_id);
    if (it != r->readers.end()) {
        Reader* rd = it->second.get();
        if (rd->guarantee) {
            auto os = rd->open_spans.find(span_begin);
            if (os != rd->open_spans.end()) rd->open_spans.erase(os);
            // consumed frontier = the released span's END (the reader
            // read those bytes); only forget the end once no
            // duplicate-begin span remains open
            int64_t span_end = span_begin;
            auto ie = rd->open_span_ends.find(span_begin);
            if (ie != rd->open_span_ends.end()) {
                span_end = ie->second;
                if (rd->open_spans.find(span_begin)
                        == rd->open_spans.end())
                    rd->open_span_ends.erase(ie);
            }
            if (span_end > rd->release_high)
                rd->release_high = span_end;
            // advance to the oldest still-open span, else to the
            // high-water RELEASED end (out-of-order releases must
            // not park the guarantee at an already-released begin)
            int64_t g = rd->open_spans.empty()
                        ? rd->release_high : *rd->open_spans.begin();
            if (g > rd->guarantee_offset) rd->guarantee_offset = g;
        }
    }
    r->nread_open -= 1;
    // quiescence point for deferred resize: "the oldest open span
    // releases" — apply once no span at all remains open
    r->maybe_apply_pending_locked();
    r->write_cv.notify_all();
    r->span_cv.notify_all();
    return BFT_OK;
}

int bft_ring_overwritten_in(void* ring_, long long begin, long long nbyte,
                            long long* out) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r || !out) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    int64_t ov = std::min<int64_t>(r->tail - begin, nbyte);
    *out = std::max<int64_t>(ov, 0);
    return BFT_OK;
}

int bft_ring_tail_head(void* ring_, long long* tail, long long* head) {
    Ring* r = static_cast<Ring*>(ring_);
    if (!r) return BFT_ERR_INVALID;
    std::lock_guard<std::mutex> lk(r->mtx);
    if (tail) *tail = r->tail;
    if (head) *head = r->head;
    return BFT_OK;
}

int bft_version(void) { return 1; }

}  // extern "C"
